"""Prometheus text-exposition parsing — the ONE canonical implementation.

Promoted out of ``tools/obs_report.py --url`` (PR 10) so the metric
federator (``fleetobs.py``) and the CLI report share a single parser
instead of drifting copies. Deliberately pure stdlib with **no package
imports**: ``tools/obs_report.py`` loads this file by path (it must stay
importable without jax), and the federator imports it as a sibling
module.

``parse_text(text)`` returns a snapshot-shaped dict — the same schema
``MetricsRegistry.snapshot()`` produces (``counters``/``gauges``/
``histograms`` keyed ``name{k=v,...}`` with sorted, unescaped labels) —
plus ``types``/``help`` maps carrying the ``# TYPE`` / ``# HELP``
metadata, and for histograms-as-summaries the parsed p50/p90/p99 +
sum/count (+ derived mean). ``scrape(url)`` GETs ``<url>/metrics`` and
parses the body.

Label values round-trip through the exposition escaping rules
(``\\`` / ``\"`` / ``\n``), matching ``registry._prom_labels`` — tested
end to end in ``tests/test_fleetobs.py``.
"""
import collections
import re
import urllib.request

_SAMPLE_RE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
_QUANTILE_TO_PCTL = {'0.5': 'p50', '0.9': 'p90', '0.99': 'p99'}


def unescape_label(v):
    """Invert the exposition escaping (``\\\\`` / ``\\"`` / ``\\n``)."""
    return (v.replace('\\\\', '\x00').replace('\\"', '"')
            .replace('\\n', '\n').replace('\x00', '\\'))


def parse_labels(raw):
    """``k1="v1",k2="v2"`` → dict with unescaped values."""
    return {k: unescape_label(v) for k, v in _LABEL_RE.findall(raw or '')}


def fmt_key(name, labels):
    """Canonical snapshot key: ``name`` or ``name{k=v,...}`` (sorted) —
    the same shape ``registry.fmt_key`` emits."""
    if not labels:
        return name
    inner = ','.join(f'{k}={v}' for k, v in sorted(labels.items()))
    return f'{name}{{{inner}}}'


def parse_text(text):
    """Parse a Prometheus text exposition into a snapshot-shaped dict.

    Returns ``{'counters': {key: num}, 'gauges': {key: num},
    'histograms': {key: {count,sum,mean,p50,p90,p99}}, 'types':
    {name: type}, 'help': {name: help_text}}``. Summary quantiles other
    than 0.5/0.9/0.99 are dropped (the registry only exports those
    three); unparseable lines are skipped, never fatal — a scrape of a
    foreign exporter degrades instead of raising.
    """
    types, helps, key_labels = {}, {}, {}
    snap = {'counters': {}, 'gauges': {}, 'histograms': {}}
    summaries = collections.defaultdict(dict)
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith('#'):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == 'TYPE':
                types[parts[2]] = parts[3].strip()
            elif len(parts) >= 3 and parts[1] == 'HELP':
                helps[parts[2]] = unescape_label(
                    parts[3] if len(parts) > 3 else '')
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, raw_labels, raw_val = m.groups()
        try:
            val = float(raw_val)
        except ValueError:
            continue
        if val == int(val):
            val = int(val)
        labels = parse_labels(raw_labels)
        quantile = labels.pop('quantile', None)
        base, field = name, None
        if name.endswith('_sum') and types.get(name[:-4]) == 'summary':
            base, field = name[:-4], 'sum'
        elif name.endswith('_count') and types.get(name[:-6]) == 'summary':
            base, field = name[:-6], 'count'
        elif quantile is not None:
            field = _QUANTILE_TO_PCTL.get(quantile)
            if field is None:
                continue
        key = fmt_key(base, labels)
        key_labels[key] = labels
        if field is not None:
            summaries[key][field] = val
        elif types.get(name) == 'gauge':
            snap['gauges'][key] = val
        else:
            snap['counters'][key] = val
    for key, st in summaries.items():
        if st.get('count'):
            st['mean'] = st.get('sum', 0.0) / st['count']
        snap['histograms'][key] = st
    snap['types'] = types
    snap['help'] = helps
    # exact per-key label dicts: consumers (the federator) must not have
    # to re-split canonical keys, which would corrupt label values that
    # themselves contain ',' or '='
    snap['labels'] = key_labels
    return snap


def scrape(url, timeout=10):
    """GET ``<url>/metrics`` (appending the path when absent) and parse
    the body with :func:`parse_text`."""
    if not url.rstrip('/').endswith('/metrics'):
        url = url.rstrip('/') + '/metrics'
    with urllib.request.urlopen(url, timeout=timeout) as r:
        text = r.read().decode('utf-8')
    return parse_text(text)


def split_key(key):
    """Invert :func:`fmt_key`: ``name{k=v,...}`` → ``(name, labels)``.
    Label VALUES here are already unescaped; splitting is on the raw
    ``,``/``=`` separators, which the registry's own keys never contain
    escaped (keys are canonical, not exposition text)."""
    if '{' not in key:
        return key, {}
    name, inner = key.split('{', 1)
    inner = inner.rstrip('}')
    labels = {}
    for part in inner.split(','):
        if '=' in part:
            k, v = part.split('=', 1)
            labels[k] = v
    return name, labels
