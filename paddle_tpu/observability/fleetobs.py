"""Fleet observability plane: metric federation, cross-replica request
stitching, and on-demand device profiling.

PRs 12-13 made paddle_tpu a fleet — N replicas behind a
:class:`~..serving.FleetRouter`, multi-tenant ``ModelHost``\\ s — but the
telemetry plane stayed per-process: one registry, one flight recorder,
one ``/metrics``. This module is the pane of glass over all of it:

- :class:`MetricFederator` merges N metric sources into fleet-level
  series. A source is an in-process replica (a :class:`~..serving.fleet.
  ReplicaSet`'s engines, distinguished by their ``engine`` label in the
  shared process registry), an in-process :class:`~..serving.host.
  ModelHost`'s hosted models, a whole ``MetricsRegistry``, or a remote
  ``/metrics`` URL parsed by the shared exposition parser
  (``promparse.py``). Every series is re-emitted with a ``replica``
  label, and **semantic aggregates** are computed across replicas:
  counters are SUMMED (bit-equal to the per-replica total — integer
  addition), gauges are folded per registered semantics
  (:func:`register_gauge_semantics` — ``sum`` by default, ``min`` for
  binding constraints like HBM watermarks, ``mean`` for ratios like
  MFU), and histogram quantiles are merged from the sources' windowed
  sample buffers (true merged-window percentiles for in-process
  sources; for URL sources, which only expose p50/p90/p99, the
  fleet quantile degrades to the conservative per-replica maximum).
  Per-replica **staleness gauges** (``fleet.obs.staleness_s``) and
  ``fleet.obs.scrape_errors`` make a dead or unreachable replica
  visible in the federated exposition itself.
- :func:`stitch` reassembles ONE end-to-end timeline for a request that
  left per-attempt records in multiple flight recorders (failover,
  hedging, split requests): all parts are found by rid (including the
  recorders' evicted archives), events are merged on the wall clock,
  exact duplicates (the same record reached through two sources) are
  dropped, and per-attempt segments are derived from the
  ``route``/``failover``/``hedge`` annotations the fleet router stamps.
- :func:`capture_profile` is bounded on-demand ``jax.profiler`` device
  tracing for a RUNNING service: one capture at a time (a concurrent
  request raises :class:`ProfileBusyError` — HTTP 409 on the server),
  window clamped to ``MAX_PROFILE_WINDOW_MS``, artifacts written to a
  directory plus a ``summary.json``. This is what lets the ROADMAP
  item-5 measurement campaign pull real device traces from live
  traffic instead of hand-run scripts.
- :class:`FleetObs` wires the three together and attaches them to a
  telemetry server: ``FleetObs().watch_router(router).serve(port=0)``
  gives an aggregated ``/metrics``, ``/debug/fleet`` (replica + host
  tables), ``/debug/requests?id=`` (stitched timelines), and
  ``/debug/profile?ms=N``.

Disabled mode (``PADDLE_TPU_OBS=0``): ``capture_profile`` returns
``{'disabled': True}`` without touching the profiler, and ``FleetObs.
serve`` returns the shared ``NULL_SERVER`` — fully inert.

Env knobs: ``PADDLE_TPU_OBS_PROFILE_CAP_MS`` (capture ceiling, default
10000), ``PADDLE_TPU_OBS_PROFILE_DIR`` (artifact root, default a fresh
temp dir per capture).
"""
import json
import os
import tempfile
import threading
import time
import urllib.parse
import urllib.request

from . import promparse
from . import reqtrace as _reqtrace
from .registry import (_prom_help, _prom_labels, _prom_name, cfg, counter,
                       gauge, percentile, registry)

ENV_PROFILE_CAP = 'PADDLE_TPU_OBS_PROFILE_CAP_MS'
ENV_PROFILE_DIR = 'PADDLE_TPU_OBS_PROFILE_DIR'
ENV_PROFILE_KEEP = 'PADDLE_TPU_OBS_PROFILE_KEEP'

MAX_PROFILE_WINDOW_MS = float(os.environ.get(ENV_PROFILE_CAP, 10_000.0))
PROFILE_DIR_PREFIX = 'pt_profile_'

_QUANTS = ((50, 'p50', '0.5'), (90, 'p90', '0.9'), (99, 'p99', '0.99'))


# ---------------------------------------------------------------------------
# gauge aggregation semantics
# ---------------------------------------------------------------------------

_semantics_lock = threading.Lock()
# mangled family name -> 'sum' | 'min' | 'max' | 'mean' | 'last'
_GAUGE_SEMANTICS = {
    # the binding constraint across replicas is the smallest budget
    'host_hbm_watermark_bytes': 'min',
    # ratios average; summing MFU across replicas would exceed 1.0
    'perf_mfu': 'mean',
    'perf_mfu_measured': 'mean',
    'gen_occupancy': 'mean',
    'gen_page_utilization': 'mean',
    'devtime_overlap_fraction': 'mean',
    'devtime_idle_pct': 'mean',
    'goodput_ratio': 'mean',
    # liveness-style gauges: the worst replica is the story
    'fleet_obs_staleness_s': 'max',
    'devtime_straggler_skew_ms': 'max',
}
_VALID_SEMANTICS = ('sum', 'min', 'max', 'mean', 'last')


def register_gauge_semantics(name, how):
    """Declare how a gauge family federates across replicas (default:
    ``sum``). ``name`` may be dotted (``host.hbm_watermark_bytes``) or
    already exposition-mangled; ``how`` is one of sum/min/max/mean/last.
    """
    if how not in _VALID_SEMANTICS:
        raise ValueError(f'semantics must be one of {_VALID_SEMANTICS}, '
                         f'got {how!r}')
    with _semantics_lock:
        _GAUGE_SEMANTICS[_prom_name(name)] = how


def gauge_semantics(name):
    with _semantics_lock:
        return _GAUGE_SEMANTICS.get(_prom_name(name), 'sum')


def _fold_gauge(how, vals):
    if not vals:
        return 0.0
    if how == 'min':
        return min(vals)
    if how == 'max':
        return max(vals)
    if how == 'mean':
        return sum(vals) / len(vals)
    if how == 'last':
        return vals[-1]
    return sum(vals)


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

def _registry_snapshot(reg, engine_label=None):
    """Snapshot a :class:`MetricsRegistry` into the promparse schema.

    ``engine_label`` projects the shared process registry onto ONE
    in-process replica: only series carrying ``engine == engine_label``
    are taken, and the engine label itself is dropped (the federator
    re-keys by ``replica`` — keeping both would stop identical series
    from different replicas from aggregating). Histograms carry their
    raw windowed samples so fleet percentiles are computed over the
    MERGED window, not averaged quantiles."""
    snap = {'counters': {}, 'gauges': {}, 'histograms': {},
            'labels': {}, 'types': {}, 'help': {}}
    for name, t, children, help_text in reg._items():
        pname = _prom_name(name)
        for c in children:
            labels = dict(c.labels)
            if engine_label is not None:
                if labels.pop('engine', None) != engine_label:
                    continue
            elif 'engine' in labels:
                # un-projected registry source: engine-labeled series
                # belong to the per-replica projections, not the
                # process-level view (they would double-count)
                continue
            key = promparse.fmt_key(pname, labels)
            snap['labels'][key] = labels
            snap['types'][pname] = ('summary' if t == 'histogram' else t)
            snap['help'][pname] = help_text
            if t == 'histogram':
                st = c.stats()
                with c._lock:
                    st['samples'] = list(c._samples)
                snap['histograms'][key] = st
            elif t == 'counter':
                snap['counters'][key] = c.value
            else:
                snap['gauges'][key] = c.value
    return snap


class _RegistrySource:
    """One whole registry as one replica (private registries, tests)."""

    def __init__(self, name, reg):
        self.name = name
        self._reg = reg

    def collect_all(self, now):
        return [(self.name, _registry_snapshot(self._reg), True, None)]


class _URLSource:
    """A remote replica's ``/metrics``, parsed by the shared parser."""

    def __init__(self, name, url, timeout=5.0):
        self.name = name
        self.url = url
        self.timeout = timeout

    def collect_all(self, now):
        try:
            snap = promparse.scrape(self.url, timeout=self.timeout)
            return [(self.name, snap, True, None)]
        except Exception as e:
            return [(self.name, None, False,
                     f'{type(e).__name__}: {e}'[:200])]


class _ReplicaSetSource:
    """Every replica of an in-process :class:`ReplicaSet`, one logical
    source per replica: the shared process registry projected onto each
    replica's ``engine`` label. A replica that is no longer READY or
    DRAINING stops refreshing — its cached series go stale, which is
    exactly what the staleness gauge reports."""

    def __init__(self, rset):
        self._rset = rset

    def collect_all(self, now):
        reg = registry()
        out = []
        for rep in self._rset.snapshot():
            fresh = rep.state in ('ready', 'draining')
            try:
                label = rep.label
            except Exception:
                fresh, label = False, None
            if not fresh or label is None:
                out.append((rep.name, None, False, None))
                continue
            out.append((rep.name, _registry_snapshot(reg, label), True,
                        None))
        return out


class _HostSource:
    """Every hosted model of an in-process :class:`ModelHost`; evicted
    models (no engine) stop refreshing and read as stale, same as dead
    replicas."""

    def __init__(self, host):
        self._host = host

    def collect_all(self, now):
        reg = registry()
        out = []
        for mname, m in list(getattr(self._host, '_models', {}).items()):
            rep_name = f'{mname}@{self._host.name}'
            label = m.engine_label
            if m.state != 'live' or not label:
                out.append((rep_name, None, False, None))
                continue
            out.append((rep_name, _registry_snapshot(reg, label), True,
                        None))
        return out


# ---------------------------------------------------------------------------
# the federator
# ---------------------------------------------------------------------------

class FederatedSnapshot:
    """One collection pass over every source: per-replica rows plus the
    computed fleet aggregates, renderable as JSON or as a Prometheus
    text exposition."""

    def __init__(self, name, families, staleness, errors, collect_ms):
        self.name = name
        self.families = families   # pname -> {'type','help','rows'}
        self.staleness = staleness  # replica -> seconds (None = never)
        self.errors = errors        # replica -> last error string
        self.collect_ms = collect_ms
        self.ts = time.time()

    # ---- aggregate math --------------------------------------------------
    @staticmethod
    def _merge_hist(vals):
        """Fleet histogram row from per-replica stat dicts: counts and
        sums add; quantiles come from the MERGED sample windows when the
        sources expose them (in-process registries do), else degrade to
        the conservative per-replica maximum (URL sources only carry
        p50/p90/p99)."""
        out = {'count': sum(int(v.get('count', 0) or 0) for v in vals),
               'sum': sum(float(v.get('sum', 0.0) or 0.0) for v in vals)}
        if all('samples' in v for v in vals):
            merged = [s for v in vals for s in v['samples']]
            for q, pq, _ in _QUANTS:
                out[pq] = percentile(merged, q)
            out['merged_window'] = True
        else:
            for q, pq, _ in _QUANTS:
                qs = [v[pq] for v in vals
                      if v.get(pq) is not None]
                out[pq] = max(qs) if qs else None
            out['merged_window'] = False
        if out['count']:
            out['mean'] = out['sum'] / out['count']
        return out

    def aggregate(self, pname, labels=None):
        """The fleet-level value for one family/label row (None when the
        family is unknown)."""
        fam = self.families.get(_prom_name(pname))
        if fam is None:
            return None
        lk = tuple(sorted((labels or {}).items()))
        row = fam['rows'].get(lk)
        if row is None:
            return None
        vals = [v for _, v in sorted(row['replicas'].items())]
        if fam['type'] == 'counter':
            return sum(vals)
        if fam['type'] == 'gauge':
            return _fold_gauge(gauge_semantics(pname), vals)
        return self._merge_hist(vals)

    def as_dict(self):
        """JSON view: aggregates + per-replica values per family row."""
        out = {'fleet': self.name, 'ts': self.ts,
               'collect_ms': self.collect_ms,
               'staleness_s': dict(self.staleness),
               'scrape_errors': dict(self.errors),
               'families': {}}
        for pname, fam in sorted(self.families.items()):
            rows = []
            for lk, row in sorted(fam['rows'].items()):
                rows.append({'labels': dict(row['labels']),
                             'aggregate': self.aggregate(pname,
                                                         row['labels']),
                             'replicas': {r: v for r, v in
                                          sorted(row['replicas'].items())}})
            out['families'][pname] = {'type': fam['type'],
                                      'help': fam['help'], 'rows': rows}
        return out

    # ---- exposition ------------------------------------------------------
    def _emit_value(self, lines, pname, labels, val, is_hist):
        if not is_hist:
            lines.append(f'{pname}{_prom_labels(labels)} {val}')
            return
        for _, pq, qv in _QUANTS:
            v = val.get(pq)
            if v is None:
                continue
            lines.append(
                f'{pname}{_prom_labels(dict(labels, quantile=qv))} {v}')
        lbl = _prom_labels(labels)
        lines.append(f'{pname}_sum{lbl} {val.get("sum", 0.0)}')
        lines.append(f'{pname}_count{lbl} {val.get("count", 0)}')

    def to_prometheus(self):
        """The aggregated exposition: per family, the fleet aggregate
        (no ``replica`` label) followed by every per-replica series
        (``replica=<name>``), then the federation meta-series."""
        lines = []
        for pname, fam in sorted(self.families.items()):
            is_hist = fam['type'] == 'summary'
            lines.append(f'# HELP {pname} {_prom_help(fam["help"])}')
            lines.append(f'# TYPE {pname} {fam["type"]}')
            for lk, row in sorted(fam['rows'].items()):
                agg = self.aggregate(pname, row['labels'])
                self._emit_value(lines, pname, row['labels'], agg, is_hist)
                for rep, val in sorted(row['replicas'].items()):
                    self._emit_value(
                        lines, pname, dict(row['labels'], replica=rep),
                        val, is_hist)
        lines.append('# HELP fleet_obs_staleness_s seconds since this '
                     'replica last reported fresh metrics')
        lines.append('# TYPE fleet_obs_staleness_s gauge')
        for rep, s in sorted(self.staleness.items()):
            v = round(s, 3) if s is not None else -1
            lines.append(
                f'fleet_obs_staleness_s{_prom_labels({"replica": rep})} '
                f'{v}')
        lines.append('# HELP fleet_obs_collect_ms wall time of the last '
                     'federation pass')
        lines.append('# TYPE fleet_obs_collect_ms gauge')
        lines.append(f'fleet_obs_collect_ms {self.collect_ms}')
        return '\n'.join(lines) + '\n'


class MetricFederator:
    """Merges N metric sources into fleet-level series — see the module
    docstring for the aggregation semantics. Sources are added with
    :meth:`add_registry` / :meth:`add_url` / :meth:`add_replica_set` /
    :meth:`add_host`; :meth:`collect` runs one federation pass and
    returns a :class:`FederatedSnapshot`. Collection also publishes the
    meta-series (staleness, scrape errors, collect time) into the
    process registry so the local plane sees federation health too."""

    def __init__(self, name='fleet', stale_after_s=10.0):
        self.name = name
        self.stale_after_s = float(stale_after_s)
        self._lock = threading.Lock()
        self._providers = []
        self._cache = {}          # replica -> (snap, wall_ts)
        self._errors = {}         # replica -> last error string
        self._scrape_errors = 0

    # ---- source registration ---------------------------------------------
    def add_registry(self, name, reg):
        with self._lock:
            self._providers.append(_RegistrySource(name, reg))
        return self

    def add_url(self, name, url, timeout=5.0):
        with self._lock:
            self._providers.append(_URLSource(name, url, timeout))
        return self

    def add_replica_set(self, rset):
        with self._lock:
            self._providers.append(_ReplicaSetSource(rset))
        return self

    def add_host(self, host):
        with self._lock:
            self._providers.append(_HostSource(host))
        return self

    # ---- collection ------------------------------------------------------
    def collect(self):
        t0 = time.perf_counter()
        now = time.time()
        with self._lock:
            providers = list(self._providers)
        families = {}
        staleness = {}
        for provider in providers:
            try:
                results = provider.collect_all(now)
            except Exception as e:
                name = getattr(provider, 'name', type(provider).__name__)
                results = [(name, None, False,
                            f'{type(e).__name__}: {e}'[:200])]
            for rep, snap, fresh, error in results:
                if fresh and snap is not None:
                    with self._lock:
                        self._cache[rep] = (snap, now)
                        self._errors.pop(rep, None)
                    staleness[rep] = 0.0
                else:
                    if error is not None:
                        self._note_error(rep, error)
                    with self._lock:
                        cached = self._cache.get(rep)
                    if cached is None:
                        staleness[rep] = None     # never reported
                        continue
                    snap, ts = cached
                    staleness[rep] = now - ts
                self._fold(families, rep, snap)
        collect_ms = round(1e3 * (time.perf_counter() - t0), 3)
        with self._lock:
            errors = dict(self._errors)
        self._publish_meta(staleness, collect_ms)
        return FederatedSnapshot(self.name, families, staleness, errors,
                                 collect_ms)

    def _note_error(self, rep, error):
        with self._lock:
            self._errors[rep] = error
            self._scrape_errors += 1
        counter('fleet.obs.scrape_errors', {'replica': rep},
                help='failed scrapes/collections per replica').inc()

    @staticmethod
    def _fold(families, rep, snap):
        for section, default_t in (('counters', 'counter'),
                                   ('gauges', 'gauge'),
                                   ('histograms', 'summary')):
            for key, val in snap.get(section, {}).items():
                labels = snap.get('labels', {}).get(key)
                if labels is None:
                    pname, labels = promparse.split_key(key)
                else:
                    pname = key.split('{', 1)[0]
                labels = dict(labels)
                labels.pop('replica', None)   # re-keyed below, never nested
                fam = families.setdefault(
                    pname, {'type': snap.get('types', {}).get(pname,
                                                              default_t),
                            'help': snap.get('help', {}).get(pname)
                            or pname,
                            'rows': {}})
                lk = tuple(sorted(labels.items()))
                row = fam['rows'].setdefault(
                    lk, {'labels': labels, 'replicas': {}})
                row['replicas'][rep] = val

    def _publish_meta(self, staleness, collect_ms):
        gauge('fleet.obs.sources', {'fleet': self.name},
              help='replicas contributing to the federated view') \
            .set(sum(1 for s in staleness.values() if s is not None))
        gauge('fleet.obs.collect_ms', {'fleet': self.name},
              help='wall time of the last federation pass').set(collect_ms)
        for rep, s in staleness.items():
            gauge('fleet.obs.staleness_s', {'replica': rep},
                  help='seconds since this replica last reported fresh '
                       'metrics').set(round(s, 3) if s is not None else -1)

    def to_prometheus(self):
        return self.collect().to_prometheus()


# ---------------------------------------------------------------------------
# cross-replica request stitching
# ---------------------------------------------------------------------------

def _fetch_request_parts(base_url, rid, timeout=5.0):
    url = (base_url.rstrip('/')
           + '/debug/requests?id=' + urllib.parse.quote(str(rid)))
    with urllib.request.urlopen(url, timeout=timeout) as r:
        body = json.loads(r.read().decode('utf-8'))
    return body.get('requests', [])


def stitch_records(rid, parts):
    """Merge per-attempt record dicts for one rid into a single
    end-to-end timeline. Events are ordered on the wall clock (each
    part's ``wall_start`` plus the event's ms offset); exact duplicates
    — the same record reached through two sources — collapse to one.
    Attempt segments are derived from the router's ``route`` /
    ``failover`` / ``hedge`` annotations, each with the replica it ran
    on and how it ended."""
    # dedup whole parts first (same record dict via recorder AND url)
    seen, uniq = set(), []
    for p in parts:
        if not p:
            continue
        pk = (p.get('id'), p.get('engine'), p.get('wall_start'),
              len(p.get('timeline', ())))
        if pk in seen:
            continue
        seen.add(pk)
        uniq.append(p)
    if not uniq:
        return {'id': rid, 'found': False, 'parts': 0,
                'attempts': [], 'timeline': []}
    t_origin = min(p.get('wall_start', 0.0) for p in uniq)
    merged, ev_seen = [], set()
    for p in uniq:
        w0 = p.get('wall_start', 0.0)
        src = p.get('engine', '')
        for ev in p.get('timeline', ()):
            wall = w0 + float(ev.get('t_ms', 0.0)) / 1e3
            attrs = {k: v for k, v in ev.items()
                     if k not in ('ev', 't_ms')}
            ek = (ev.get('ev'), round(wall * 1e6),
                  json.dumps(attrs, sort_keys=True, default=str))
            if ek in ev_seen:
                continue
            ev_seen.add(ek)
            entry = {'ev': ev.get('ev'),
                     't_ms': round((wall - t_origin) * 1e3, 3),
                     'source': src}
            entry.update(attrs)
            merged.append(entry)
    merged.sort(key=lambda e: (e['t_ms'], e['ev'] or ''))
    # primary part: the one whose outcome is terminal (first found wins)
    primary = next((p for p in uniq if p.get('outcome') is not None),
                   uniq[0])
    attempts, current = [], None
    for e in merged:
        rep = e.get('replica')
        if e['ev'] == 'route':
            if current is not None and current['outcome'] is None:
                current['outcome'] = 'superseded'
            current = {'replica': rep, 'start_ms': e['t_ms'],
                       'end_ms': None, 'outcome': None, 'error': None,
                       'events': 0}
            attempts.append(current)
        elif e['ev'] == 'failover':
            frm = e.get('frm')
            for a in reversed(attempts):
                if a['outcome'] is None and (frm is None
                                             or a['replica'] == frm):
                    a['outcome'] = 'failover'
                    a['error'] = e.get('error')
                    a['end_ms'] = e['t_ms']
                    break
        elif current is not None and rep in (None, current['replica']):
            current['events'] += 1
            current['end_ms'] = e['t_ms']
    final_outcome = primary.get('outcome')
    for a in attempts:
        if a['outcome'] is None:
            a['outcome'] = final_outcome or 'active'
    return {'id': rid, 'found': True, 'parts': len(uniq),
            'kind': primary.get('kind'), 'engine': primary.get('engine'),
            'outcome': final_outcome, 'error': primary.get('error'),
            'duration_ms': primary.get('duration_ms'),
            'replicas': sorted({a['replica'] for a in attempts
                                if a['replica']}),
            'attempts': attempts, 'timeline': merged}


def stitch(rid, recorders=None, urls=None):
    """Gather every record carrying ``rid`` — from the given flight
    recorders (default: this process's) and remote ``/debug/requests``
    bases — and stitch them into one timeline via
    :func:`stitch_records`. Unreachable peers are skipped (counted on
    ``fleet.obs.scrape_errors{replica=<url>}``), never fatal: a
    post-mortem tool must degrade, not crash."""
    parts = []
    for rec in (recorders if recorders is not None
                else [_reqtrace.recorder()]):
        parts.extend(rec.requests(rid=rid))
    for url in (urls or ()):
        try:
            parts.extend(_fetch_request_parts(url, rid))
        except Exception:
            counter('fleet.obs.scrape_errors', {'replica': str(url)},
                    help='failed scrapes/collections per replica').inc()
    return stitch_records(rid, parts)


# ---------------------------------------------------------------------------
# on-demand device profiling
# ---------------------------------------------------------------------------

class ProfileBusyError(RuntimeError):
    """A profiler capture is already running (the device profiler is a
    process-global singleton — two overlapping ``jax.profiler.trace``
    windows would corrupt each other). Maps to HTTP 409."""


_profile_lock = threading.Lock()


def profile_keep():
    """How many capture artifact dirs to retain (LRU by mtime)."""
    try:
        return max(1, int(os.environ.get(ENV_PROFILE_KEEP, '8')))
    except ValueError:
        return 8


def _gc_profile_dirs(current_dir):
    """Retention for on-demand captures: keep the newest ``profile_keep()``
    ``pt_profile_*`` siblings of ``current_dir`` (by mtime, the running
    capture always kept), delete the rest so repeated ``/debug/profile``
    hits cannot fill the disk. Returns the number removed (also counted
    on ``fleet.obs.profile_gc_total``)."""
    import shutil
    root = os.path.dirname(os.path.abspath(current_dir))
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    dirs = []
    for n in names:
        if not n.startswith(PROFILE_DIR_PREFIX):
            continue
        p = os.path.join(root, n)
        if not os.path.isdir(p):
            continue
        try:
            mt = os.path.getmtime(p)
        except OSError:
            continue
        dirs.append((mt, p))
    keep = profile_keep()
    dirs.sort(reverse=True)                      # newest first
    cur = os.path.abspath(current_dir)
    victims = [p for _, p in dirs[keep:] if os.path.abspath(p) != cur]
    removed = 0
    for p in victims:
        shutil.rmtree(p, ignore_errors=True)
        removed += 1
    if removed:
        counter('fleet.obs.profile_gc_total',
                help='profile artifact dirs removed by retention').inc(
                    removed)
    return removed


def capture_profile(ms=500.0, out_dir=None):
    """Capture a bounded ``jax.profiler`` device trace from the running
    process and return a summary dict.

    ``ms`` is clamped into ``(0, MAX_PROFILE_WINDOW_MS]``; the capture
    sleeps out the window on the CALLING thread while every engine
    keeps serving — the trace records exactly the live traffic.
    Artifacts land under ``out_dir`` (default: a fresh temp dir, or
    ``PADDLE_TPU_OBS_PROFILE_DIR``); the summary (window, wall time,
    artifact dir, file list, byte count) is also written there as
    ``summary.json``. Raises :class:`ProfileBusyError` while another
    capture is in flight; returns ``{'disabled': True}`` under
    ``PADDLE_TPU_OBS=0`` without touching the profiler."""
    if not cfg.enabled:
        return {'disabled': True}
    ms = min(max(float(ms), 1.0), MAX_PROFILE_WINDOW_MS)
    if not _profile_lock.acquire(blocking=False):
        raise ProfileBusyError(
            'a profiler capture is already in flight; retry after it '
            'completes')
    try:
        import jax
        if out_dir is None:
            root = os.environ.get(ENV_PROFILE_DIR)
            if root:
                os.makedirs(root, exist_ok=True)
            out_dir = tempfile.mkdtemp(prefix='pt_profile_', dir=root)
        else:
            os.makedirs(out_dir, exist_ok=True)
        t0 = time.perf_counter()
        with jax.profiler.trace(out_dir):
            time.sleep(ms / 1e3)
        wall_ms = round(1e3 * (time.perf_counter() - t0), 3)
        files, total = [], 0
        for base, _, names in os.walk(out_dir):
            for n in names:
                p = os.path.join(base, n)
                try:
                    sz = os.path.getsize(p)
                except OSError:
                    continue
                files.append({'path': os.path.relpath(p, out_dir),
                              'bytes': sz})
                total += sz
        summary = {'window_ms': ms, 'wall_ms': wall_ms,
                   'artifact_dir': os.path.abspath(out_dir),
                   'files': sorted(files, key=lambda f: f['path']),
                   'bytes': total, 'ts': time.time()}
        # host-side attribution of the capture we just wrote: per-category
        # device time, overlap fraction, measured MFU — published to the
        # registry AND embedded so /debug/profile returns analysis inline
        try:
            from . import devtime
            summary['devtime'] = devtime.attribute(out_dir, window_ms=ms)
        except Exception as e:
            summary['devtime'] = {
                'error': f'{type(e).__name__}: {e}'[:300]}
            counter('fleet.obs.profile_analyze_errors',
                    help='devtime attribution failures on captured '
                         'profiles').inc()
        try:
            with open(os.path.join(out_dir, 'summary.json'), 'w') as f:
                json.dump(summary, f, indent=1, sort_keys=True)
        except OSError:
            pass
        _gc_profile_dirs(out_dir)
        counter('fleet.obs.profiles',
                help='on-demand device profile captures').inc()
        return summary
    finally:
        _profile_lock.release()


def profile_in_flight():
    """True while a capture holds the profiler (the 409 predicate)."""
    if _profile_lock.acquire(blocking=False):
        _profile_lock.release()
        return False
    return True


# ---------------------------------------------------------------------------
# the wiring object
# ---------------------------------------------------------------------------

class FleetObs:
    """One pane of glass over routers, hosts, and remote peers.

    Aggregation state for a telemetry server: the federator behind the
    aggregated ``/metrics``, the router/host references behind
    ``/debug/fleet``, and the recorder/peer set behind stitched
    ``/debug/requests?id=``. Attach with ``serve(port=0)`` or pass to
    ``observability.serve_telemetry(fleetobs=...)``."""

    def __init__(self, name='fleet', federator=None):
        self.name = name
        self.federator = (federator if federator is not None
                          else MetricFederator(name=name))
        self._lock = threading.Lock()
        self._routers = []
        self._hosts = []
        self._peer_urls = {}      # name -> base url (requests + metrics)

    # ---- watching --------------------------------------------------------
    def watch_router(self, router):
        """Federate a :class:`FleetRouter`'s replicas and include them
        in the ``/debug/fleet`` replica table."""
        with self._lock:
            self._routers.append(router)
        self.federator.add_replica_set(router.set)
        return self

    def watch_replica_set(self, rset):
        self.federator.add_replica_set(rset)
        return self

    def watch_host(self, host):
        with self._lock:
            self._hosts.append(host)
        self.federator.add_host(host)
        return self

    def add_peer(self, name, base_url):
        """A remote replica process: its ``/metrics`` joins the
        federation and its ``/debug/requests`` joins the stitcher."""
        self.federator.add_url(name, base_url)
        with self._lock:
            self._peer_urls[name] = base_url.rstrip('/')
        return self

    # ---- views -----------------------------------------------------------
    def to_prometheus(self):
        return self.federator.to_prometheus()

    def stitch(self, rid):
        with self._lock:
            urls = list(self._peer_urls.values())
        return stitch(rid, urls=urls)

    def fleet_table(self):
        """The ``/debug/fleet`` document: a replica table (lifecycle
        state, warm, breaker, queue depth, queue-wait p99) and a host
        table (HBM watermark/used, resident/evicted models, lane sheds,
        tenant inflight)."""
        with self._lock:
            routers = list(self._routers)
            hosts = list(self._hosts)
        reg = registry()
        replicas = []
        for router in routers:
            for rep in router.set.snapshot():
                row = {'fleet': router.name, 'replica': rep.name,
                       'state': rep.state, 'kind': rep.kind}
                try:
                    p = rep.probe()
                except Exception as e:
                    p = {'error': type(e).__name__}
                row.update({k: p.get(k) for k in
                            ('warm', 'breaker', 'queue_depth',
                             'queue_capacity', 'ready')})
                try:
                    h = reg.find('serve.queue_wait_ms',
                                 {'engine': rep.label})
                except Exception:
                    h = None
                row['queue_wait_p99_ms'] = (h.percentile(99)
                                            if h is not None else None)
                replicas.append(row)
        host_rows = [h.debug_table() for h in hosts]
        return {'ts': time.time(),
                'replicas': replicas,
                'hosts': host_rows,
                'profile_in_flight': profile_in_flight()}

    def serve(self, port=0, host='127.0.0.1'):
        """Start a telemetry server with this plane attached (aggregated
        ``/metrics``, ``/debug/fleet``, stitched ``?id=``,
        ``/debug/profile``). Returns ``NULL_SERVER`` when observability
        is disabled."""
        from .server import serve_telemetry
        return serve_telemetry(port=port, host=host, fleetobs=self)
