"""Request-scoped tracing: per-request timelines + a bounded flight recorder.

Aggregate histograms (``serve.*`` / ``gen.*``) answer "how is the fleet
doing"; they cannot answer "what happened to THIS request". The engines'
iteration-level scheduling makes that worse — one user sequence rides many
decode steps, may be evicted and readmitted, and its TTFT depends on queue
position — none of which is recoverable from percentiles. This module is
the missing per-request layer:

- ``start_request(kind, engine=...)`` mints a request ID at ``submit()``
  time and returns a :class:`RequestRecord` that rides the request across
  the submit→dispatch/scheduler thread boundary;
- the engines ``note()`` lifecycle events into it (enqueue, admit,
  bucket/slot assignment, prefill, decode-step windows, eviction/requeue,
  first stream emission, retire) with millisecond offsets from enqueue;
- ``finish(outcome)`` moves the record into a bounded **flight recorder**
  ring of the last N completed requests, where slow and failed requests
  are retained preferentially over healthy ones when the ring evicts —
  the requests you debug are exactly the ones a plain FIFO would have
  already dropped.

Request IDs also appear as args on the engines' Chrome-trace spans
(``serve.batch`` / ``gen.prefill`` / ``gen.decode_step``), so one request
can be followed through the Perfetto view, and ``/debug/requests`` on the
telemetry server (``server.py``) exposes the ring over HTTP.

Disabled mode (``PADDLE_TPU_OBS=0``): ``start_request`` returns one shared
``NULL_RECORD`` whose methods are no-ops — no IDs, no timelines, no ring.

Env knobs: ``PADDLE_TPU_OBS_REQ_CAP`` (ring capacity, default 256),
``PADDLE_TPU_OBS_SLOW_MS`` (slow-request retention threshold, default
1000 ms).
"""
import collections
import itertools
import os
import threading
import time

from .registry import cfg, counter, gauge

ENV_REQ_CAP = 'PADDLE_TPU_OBS_REQ_CAP'
ENV_SLOW_MS = 'PADDLE_TPU_OBS_SLOW_MS'

_OK_OUTCOMES = ('ok',)


def _env_num(name, default, cast):
    try:
        return cast(os.environ.get(name, default))
    except (TypeError, ValueError):
        return cast(default)


class RequestRecord:
    """One request's timeline. Created by ``FlightRecorder.start``; engines
    append events from whichever thread is driving the request at the time
    (its lock makes that safe), then ``finish(outcome)`` seals it."""

    __slots__ = ('rid', 'kind', 'engine', 'attrs', 'wall_start', 'timeline',
                 'outcome', 'error', 'duration_ms', '_mono0', '_lock',
                 '_parts_left', '_recorder')

    def __init__(self, rid, kind, engine='', attrs=None, recorder=None):
        self.rid = rid
        self.kind = kind
        self.engine = engine
        self.attrs = dict(attrs) if attrs else {}
        self.wall_start = time.time()
        self._mono0 = time.perf_counter()
        self.timeline = []
        self.outcome = None          # None while in flight
        self.error = None            # error class name on failure
        self.duration_ms = None
        self._lock = threading.Lock()
        self._parts_left = 1
        self._recorder = recorder

    # ---- engine-side API -------------------------------------------------
    def note(self, ev, **attrs):
        """Append one timeline event at the current ms offset."""
        entry = {'ev': ev,
                 't_ms': round((time.perf_counter() - self._mono0) * 1e3, 3)}
        if attrs:
            entry.update(attrs)
        with self._lock:
            if self.outcome is None:
                self.timeline.append(entry)
        return self

    def note_decode(self, pos):
        """Record participation in one decode step, coalescing consecutive
        steps into a single window entry — a 2k-token sequence must not
        grow a 2k-entry timeline."""
        now_ms = round((time.perf_counter() - self._mono0) * 1e3, 3)
        with self._lock:
            if self.outcome is not None:
                return self
            last = self.timeline[-1] if self.timeline else None
            if last is not None and last['ev'] == 'decode':
                last['steps'] += 1
                last['t_last_ms'] = now_ms
                last['last_pos'] = int(pos)
            else:
                self.timeline.append({'ev': 'decode', 't_ms': now_ms,
                                      't_last_ms': now_ms, 'steps': 1,
                                      'last_pos': int(pos)})
        return self

    def expect_parts(self, n):
        """A split request retires once per chunk; the record finishes on
        the last chunk (``part_retired`` returning True)."""
        with self._lock:
            self._parts_left = max(1, int(n))
        return self

    def part_retired(self):
        with self._lock:
            self._parts_left -= 1
            return self._parts_left <= 0

    def finish(self, outcome, error=None):
        """Seal the record (idempotent — the first outcome wins) and hand
        it to the flight recorder's retention ring."""
        with self._lock:
            if self.outcome is not None:
                return self
            self.outcome = str(outcome)
            if error is not None:
                self.error = type(error).__name__ \
                    if isinstance(error, BaseException) else str(error)
            self.duration_ms = round(
                (time.perf_counter() - self._mono0) * 1e3, 3)
        if self._recorder is not None:
            self._recorder._complete(self)
        return self

    # ---- serialization ---------------------------------------------------
    def to_dict(self):
        with self._lock:
            return {'id': self.rid, 'kind': self.kind, 'engine': self.engine,
                    'wall_start': self.wall_start,
                    'outcome': self.outcome, 'error': self.error,
                    'duration_ms': self.duration_ms,
                    'attrs': dict(self.attrs),
                    'timeline': [dict(e) for e in self.timeline]}


class _NullRecord:
    """Shared no-op record for disabled mode: no ID, no timeline, no ring."""

    __slots__ = ()
    rid = ''
    kind = ''
    engine = ''
    outcome = None
    error = None
    duration_ms = None
    timeline = ()
    attrs = {}

    def note(self, ev, **attrs):
        return self

    def note_decode(self, pos):
        return self

    def expect_parts(self, n):
        return self

    def part_retired(self):
        return False

    def finish(self, outcome, error=None):
        return self

    def to_dict(self):
        return {}


NULL_RECORD = _NullRecord()


class FlightRecorder:
    """Bounded ring of the last N *completed* requests plus the in-flight
    set. Eviction is outcome-aware: when the ring is full the oldest
    *healthy* (ok + fast) record goes first, so slow/failed requests — the
    ones worth debugging — survive longer than their arrival order."""

    def __init__(self, capacity=None, slow_ms=None):
        self.capacity = int(capacity if capacity is not None
                            else _env_num(ENV_REQ_CAP, 256, int))
        self.slow_ms = float(slow_ms if slow_ms is not None
                             else _env_num(ENV_SLOW_MS, 1000.0, float))
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._active = {}            # rid -> RequestRecord
        self._done = []              # completion order, oldest first
        # records evicted from the ring stay findable BY ID for one more
        # generation: the cross-replica stitcher must be able to recover
        # every part of a split or failed-over request even after fresh
        # traffic has cycled the main ring (bounded — never a leak)
        self._evicted = collections.deque(maxlen=self.capacity)

    # ---- lifecycle -------------------------------------------------------
    def start(self, kind, engine='', **attrs):
        rid = f'{kind}-{os.getpid():x}-{next(self._ids):06d}'
        rec = RequestRecord(rid, kind, engine, attrs, recorder=self)
        with self._lock:
            self._active[rid] = rec
            n_active = len(self._active)
        lbl = {'kind': kind}
        if 'tenant' in attrs:
            # per-tenant request accounting: a ModelHost threads the tenant
            # through here so /metrics can attribute load per tenant
            lbl['tenant'] = str(attrs['tenant'])
        counter('request.started', lbl).inc()
        gauge('request.active').set(n_active)
        return rec

    def _notable(self, rec):
        """Retained preferentially: failed, slow, evicted, or failed-over /
        hedged requests (a request that survived a replica death is exactly
        the one worth a post-mortem even though its outcome reads ok)."""
        if rec.outcome not in _OK_OUTCOMES:
            return True
        if rec.duration_ms is not None and rec.duration_ms >= self.slow_ms:
            return True
        return any(e.get('ev') in ('evict', 'failover', 'hedge')
                   for e in rec.timeline)

    def _complete(self, rec):
        with self._lock:
            self._active.pop(rec.rid, None)
            self._done.append(rec)
            while len(self._done) > self.capacity:
                victim = next((i for i, r in enumerate(self._done)
                               if not self._notable(r)), 0)
                self._evicted.append(self._done.pop(victim))
            n_active = len(self._active)
        lbl = {'kind': rec.kind, 'outcome': rec.outcome or '?'}
        if 'tenant' in rec.attrs:
            lbl['tenant'] = str(rec.attrs['tenant'])
        counter('request.completed', lbl).inc()
        gauge('request.active').set(n_active)

    # ---- queries ---------------------------------------------------------
    def lookup(self, rid):
        """The record dict for ``rid`` (in flight, completed, or evicted
        from the ring but still in the archive), or None."""
        with self._lock:
            rec = self._active.get(rid)
            if rec is None:
                rec = next((r for r in self._done if r.rid == rid), None)
            if rec is None:
                rec = next((r for r in self._evicted if r.rid == rid), None)
        return rec.to_dict() if rec is not None else None

    def requests(self, outcome=None, rid=None, limit=None, tenant=None):
        """Newest-first list of record dicts. ``outcome`` filters completed
        records ('ok', 'error', 'expired', 'rejected', or 'active' for the
        in-flight set); ``rid`` returns EVERY record carrying that ID —
        searching the in-flight set, the completed ring, AND the evicted
        archive — so the cross-replica stitcher (``fleetobs.stitch``) and
        ``/debug/requests?id=`` find all parts of a split or failed-over
        request; ``tenant`` filters on the ``tenant`` attr a ModelHost
        stamps onto every request it routes (per-tenant blast-radius
        triage)."""
        if rid:
            with self._lock:
                found = []
                rec = self._active.get(rid)
                if rec is not None:
                    found.append(rec)
                found.extend(r for r in self._done if r.rid == rid)
                found.extend(r for r in self._evicted if r.rid == rid)
            return [r.to_dict() for r in found]
        with self._lock:
            done = list(reversed(self._done))
            active = list(self._active.values())
        if outcome == 'active':
            recs = active
        elif outcome:
            recs = [r for r in done if r.outcome == outcome]
        else:
            recs = active + done
        if tenant:
            recs = [r for r in recs if r.attrs.get('tenant') == tenant]
        if limit is not None:
            recs = recs[:max(0, int(limit))]
        return [r.to_dict() for r in recs]

    def set_capacity(self, n):
        with self._lock:
            self.capacity = max(1, int(n))
            self._evicted = collections.deque(self._evicted,
                                              maxlen=self.capacity)
            while len(self._done) > self.capacity:
                victim = next((i for i, r in enumerate(self._done)
                               if not self._notable(r)), 0)
                self._evicted.append(self._done.pop(victim))
        return self.capacity

    def __len__(self):
        with self._lock:
            return len(self._done)

    def reset(self):
        with self._lock:
            self._active.clear()
            self._done.clear()
            self._evicted.clear()


class _NullRecorder:
    """Shared no-op recorder for disabled mode."""

    __slots__ = ()
    capacity = 0
    slow_ms = 0.0

    def start(self, kind, engine='', **attrs):
        return NULL_RECORD

    def lookup(self, rid):
        return None

    def requests(self, outcome=None, rid=None, limit=None, tenant=None):
        return []

    def set_capacity(self, n):
        return 0

    def __len__(self):
        return 0

    def reset(self):
        pass


NULL_RECORDER = _NullRecorder()

_recorder = FlightRecorder()


def recorder():
    """The process-wide flight recorder (``NULL_RECORDER`` when disabled)."""
    if not cfg.enabled:
        return NULL_RECORDER
    return _recorder


def start_request(kind, engine='', **attrs):
    """Mint a request ID and start its timeline (``NULL_RECORD`` when
    observability is disabled — zero allocation on the hot path)."""
    if not cfg.enabled:
        return NULL_RECORD
    return _recorder.start(kind, engine, **attrs)


def reset_requests():
    _recorder.reset()
