"""Live telemetry plane: ``/metrics`` + ``/healthz`` + ``/readyz`` +
``/debug/*`` over a stdlib ``http.server`` daemon thread.

Everything PRs 4/6 measure is in-process only (snapshot files, atexit
dumps); this module is the network face that lets an external agent — a
Prometheus scraper, a load balancer, a replica router — observe a running
engine. No new dependencies: ``ThreadingHTTPServer`` on a daemon thread,
bound to **localhost** by default (expose it beyond the host through your
own ingress/auth, not by flipping the bind address casually).

Endpoints:

- ``GET /metrics``  — the registry's Prometheus text exposition, with the
  correct ``text/plain; version=0.0.4`` content type (byte-identical to
  ``observability.to_prometheus()``).
- ``GET /healthz``  — liveness: 200 + uptime while the process serves.
- ``GET /readyz``   — readiness: every registered probe must pass (engines
  register warmup-complete AND circuit-breaker-closed AND
  queue-below-backpressure); 503 + per-check detail otherwise.
- ``GET /debug/requests`` — the flight-recorder ring (``reqtrace.py``),
  filterable by ``?id=<rid>`` / ``?outcome=ok|error|expired|active`` /
  ``?limit=N``.
- ``GET /debug/trace?ms=N`` — on-demand bounded Chrome-trace capture: the
  handler marks the trace clock, waits N ms (clamped), and returns the
  events recorded in that window as a chrome://tracing-loadable document;
  ``?cap=N`` bounds the ring for the capture via ``set_trace_cap``.
- ``GET /debug/slo`` — every SLO watcher rule's ok/firing state.
- ``GET /debug/fleet`` — replica + host tables when a
  :class:`~.fleetobs.FleetObs` plane is attached (404 otherwise).
- ``GET /debug/profile?ms=N`` — bounded on-demand ``jax.profiler``
  device capture (``fleetobs.capture_profile``): one capture at a time
  (a concurrent request gets **409**), window clamped to
  ``fleetobs.MAX_PROFILE_WINDOW_MS``, summary JSON (artifact dir, file
  list, byte count, and the inline ``devtime`` attribution — per-category
  device time, overlap fraction, measured MFU) returned; 503 when
  observability is disabled.
- ``GET /debug/goodput`` — the always-on training goodput ledger
  (``goodput.snapshot()``): elapsed/goodput seconds, ratio, and badput
  seconds per cause (compile/checkpoint/data_stall/preemption/requeue).

A server with a ``FleetObs`` attached (``serve_telemetry(fleetobs=...)``
or ``FleetObs.serve()``) federates: ``/metrics`` returns the AGGREGATED
fleet exposition (per-replica series + semantic aggregates + staleness)
instead of the process registry, and ``/debug/requests?id=`` adds a
``stitched`` cross-replica timeline next to the raw records.

Start one with ``observability.serve_telemetry(port=0)`` (port 0 picks a
free port; read it back from ``server.port``), or let an engine own one:
``InferenceEngine(telemetry_port=0)`` / ``GenerationEngine(...)`` /
``Model.fit(telemetry_port=...)``.

Disabled mode (``PADDLE_TPU_OBS=0``): ``serve_telemetry`` returns the
shared ``NULL_SERVER`` — no thread, no socket.
"""
import json
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import fleetobs as _fleetobs
from . import reqtrace as _reqtrace
from . import slo as _slo
from . import trace as _trace
from .registry import cfg, counter, to_prometheus

PROM_CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'
MAX_TRACE_WINDOW_MS = 10_000.0      # /debug/trace capture ceiling

_probes_lock = threading.Lock()
_probes = {}        # name -> callable() -> {'ready': bool, ...} | bool


def add_readiness(name, probe):
    """Register a readiness probe. ``probe()`` returns a dict with a
    ``'ready'`` bool (plus any detail fields) or a bare bool; every
    registered probe must pass for ``/readyz`` to return 200. Probes are
    process-global so one server can answer for several engines."""
    with _probes_lock:
        _probes[str(name)] = probe


def remove_readiness(name):
    with _probes_lock:
        _probes.pop(str(name), None)


def readiness():
    """Aggregate readiness: ``{'ready': bool, 'checks': {name: detail}}``.
    A probe that raises marks its check (and the whole answer) not ready —
    a crashed engine must not read as servable. With no probes registered
    the process is trivially ready (liveness is the only claim)."""
    with _probes_lock:
        probes = dict(_probes)
    checks, ready = {}, True
    for name, probe in sorted(probes.items()):
        try:
            st = probe()
        except Exception as e:
            st = {'ready': False, 'error': f'{type(e).__name__}: {e}'[:200]}
        if isinstance(st, bool):
            st = {'ready': st}
        checks[name] = st
        ready = ready and bool(st.get('ready'))
    return {'ready': ready, 'checks': checks}


class _Handler(BaseHTTPRequestHandler):
    server_version = 'paddle-tpu-telemetry/1.0'
    protocol_version = 'HTTP/1.1'

    def log_message(self, fmt, *args):      # no stderr spam per request
        pass

    # ---- response helpers ------------------------------------------------
    def _send(self, code, body, ctype='application/json'):
        data = body if isinstance(body, bytes) else body.encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type', ctype)
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code, obj):
        self._send(code, json.dumps(obj, indent=1, sort_keys=True,
                                    default=str))

    # ---- routing ---------------------------------------------------------
    def do_GET(self):
        parsed = urllib.parse.urlsplit(self.path)
        path = parsed.path.rstrip('/') or '/'
        q = dict(urllib.parse.parse_qsl(parsed.query))
        counter('server.http_requests', {'path': path}).inc()
        try:
            handler = _ROUTES.get(path)
            if handler is None:
                self._send_json(404, {'error': f'unknown path {path!r}',
                                      'paths': sorted(_ROUTES)})
                return
            handler(self, q)
        except (BrokenPipeError, ConnectionResetError):
            pass                            # client went away mid-response
        except Exception as e:              # never kill the server thread
            counter('server.http_errors', {'path': path}).inc()
            try:
                self._send_json(
                    500, {'error': f'{type(e).__name__}: {e}'[:500]})
            except Exception:
                pass

    # ---- endpoints -------------------------------------------------------
    def _metrics(self, q):
        fobs = self.server._telemetry.fleetobs
        body = (fobs.to_prometheus() if fobs is not None
                else to_prometheus())
        self._send(200, body, PROM_CONTENT_TYPE)

    def _healthz(self, q):
        srv = self.server._telemetry
        self._send_json(200, {'status': 'alive', 'pid': os.getpid(),
                              'uptime_s': round(time.time() - srv.started,
                                                3)})

    def _readyz(self, q):
        r = readiness()
        self._send_json(200 if r['ready'] else 503, r)

    def _debug_requests(self, q):
        rec = _reqtrace.recorder()
        limit = q.get('limit')
        rid = q.get('id') or None
        reqs = rec.requests(outcome=q.get('outcome') or None,
                            rid=rid,
                            limit=int(limit) if limit else None,
                            tenant=q.get('tenant') or None)
        out = {'count': len(reqs), 'capacity': rec.capacity,
               'requests': reqs}
        fobs = self.server._telemetry.fleetobs
        if rid and fobs is not None:
            # the fleet view: every part of a failed-over/hedged/split
            # request (local + peers) merged into one timeline
            out['stitched'] = fobs.stitch(rid)
        self._send_json(200, out)

    def _debug_trace(self, q):
        ms = min(max(float(q.get('ms', 250.0)), 0.0), MAX_TRACE_WINDOW_MS)
        old_cap = None
        if 'cap' in q:
            old_cap = _trace.trace_cap()
            _trace.set_trace_cap(int(q['cap']))
        try:
            t0 = _trace.now_us()
            if ms > 0:
                time.sleep(ms / 1e3)        # handler thread only; the
            doc = _trace.build_trace_doc(   # engines keep running
                _trace.trace_events(since_us=t0))
        finally:
            if old_cap is not None:
                _trace.set_trace_cap(old_cap)
        doc['otherData']['capture_ms'] = ms
        body = json.dumps(doc, default=str).encode('utf-8')
        self.send_response(200)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Disposition',
                         'attachment; filename="trace.json"')
        self.send_header('Content-Length', str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _debug_slo(self, q):
        rules = _slo.rule_states()
        firing = [r['rule'] for r in rules if r['state'] == 'firing']
        self._send_json(200, {'count': len(rules), 'firing': firing,
                              'rules': rules})

    def _debug_fleet(self, q):
        fobs = self.server._telemetry.fleetobs
        if fobs is None:
            self._send_json(404, {'error': 'no fleet observability plane '
                                           'attached to this server'})
            return
        self._send_json(200, fobs.fleet_table())

    def _debug_profile(self, q):
        ms = float(q.get('ms', 500.0))
        try:
            summary = _fleetobs.capture_profile(ms)
        except _fleetobs.ProfileBusyError as e:
            self._send_json(409, {'error': str(e), 'busy': True})
            return
        if summary.get('disabled'):
            self._send_json(503, {'error': 'observability disabled '
                                           '(PADDLE_TPU_OBS=0)'})
            return
        self._send_json(200, summary)

    def _debug_goodput(self, q):
        from . import goodput as _goodput
        self._send_json(200, _goodput.snapshot())


_ROUTES = {
    '/metrics': _Handler._metrics,
    '/healthz': _Handler._healthz,
    '/readyz': _Handler._readyz,
    '/debug/requests': _Handler._debug_requests,
    '/debug/trace': _Handler._debug_trace,
    '/debug/slo': _Handler._debug_slo,
    '/debug/fleet': _Handler._debug_fleet,
    '/debug/profile': _Handler._debug_profile,
    '/debug/goodput': _Handler._debug_goodput,
}


class TelemetryServer:
    """One HTTP listener on a daemon thread. ``port=0`` binds an ephemeral
    port (read back from ``.port``); the default host is localhost — the
    telemetry plane is an operator surface, not a public one."""

    def __init__(self, port=0, host='127.0.0.1', fleetobs=None):
        self.host = host
        self.fleetobs = fleetobs        # FleetObs plane (or None)
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd._telemetry = self
        self.port = self._httpd.server_address[1]
        self.started = time.time()
        self._thread = None

    @property
    def url(self):
        return f'http://{self.host}:{self.port}'

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={'poll_interval': 0.1},
                name='paddle-tpu-telemetry', daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._httpd.shutdown()
        self._httpd.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None
        with _servers_lock:
            if self in _servers:
                _servers.remove(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class _NullServer:
    """Shared no-op server for disabled mode: no socket, no thread."""

    __slots__ = ()
    host = ''
    port = 0
    url = ''
    started = 0.0
    fleetobs = None

    def start(self):
        return self

    def stop(self, timeout=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SERVER = _NullServer()

_servers_lock = threading.Lock()
_servers = []


def serve_telemetry(port=0, host='127.0.0.1', fleetobs=None):
    """Start a telemetry server (daemon thread) and return it. Attaching a
    ``FleetObs`` plane (``fleetobs=``) turns this server into the fleet
    face: federated ``/metrics``, ``/debug/fleet``, stitched
    ``/debug/requests?id=``. Returns ``NULL_SERVER`` when observability is
    disabled — fully inert."""
    if not cfg.enabled:
        return NULL_SERVER
    srv = TelemetryServer(port=port, host=host, fleetobs=fleetobs).start()
    with _servers_lock:
        _servers.append(srv)
    return srv


def servers():
    with _servers_lock:
        return list(_servers)


def shutdown_telemetry():
    """Stop every server started via ``serve_telemetry`` (tests)."""
    for srv in servers():
        srv.stop()
