"""Always-on training goodput/badput ledger.

Where does training wall-clock actually go? ``devtime.py`` answers for a
bounded capture; this module answers continuously for every ``fit()``
run, from signals the step path already emits — no profiler, no new
device work, a handful of float ops per step (well inside the <5%
observability budget).

Badput causes:

- ``compile``     — a step that retraced/compiled (the model's trace
                    counter moved during the step); the whole step
                    duration is booked, the standard goodput convention.
- ``checkpoint``  — time inside ``ckpt.save`` / ``ckpt.manager_save``
                    (framework_io books its span duration here).
- ``data_stall``  — host blocked in the batch iterator beyond the stall
                    floor (``PADDLE_TPU_GOODPUT_DATA_FLOOR_MS``, default
                    5 ms: normal prefetched next() costs less; a stall is
                    the loader failing to hide behind compute).
- ``preemption``  — restore-from-checkpoint time (``ckpt.restore``) and
                    fleet failover recovery.
- ``requeue``     — backoff sleeps inside ``fault.retry`` (the process is
                    alive but deliberately waiting to try again).

Exposed as ``goodput.ratio`` (goodput seconds ÷ elapsed run seconds),
``goodput.badput_ms{cause}`` counters, ``goodput.steps``, and the
``/debug/goodput`` endpoint (``snapshot()``). Badput noted while no run
is active still lands on the counters but does not move the ratio — a
checkpoint written outside ``fit()`` is not stealing training time.

Disabled mode (``PADDLE_TPU_OBS=0``): every entry point is a no-op.
"""
import os
import threading
import time

from .registry import cfg, registry as _registry

CAUSES = ('compile', 'checkpoint', 'data_stall', 'preemption', 'requeue')

ENV_DATA_FLOOR = 'PADDLE_TPU_GOODPUT_DATA_FLOOR_MS'


def _data_floor_s():
    try:
        return float(os.environ.get(ENV_DATA_FLOOR, '5')) / 1e3
    except ValueError:
        return 0.005


class GoodputLedger:
    """Process-wide training-time ledger. One instance (``ledger()``);
    every method is thread-safe and cheap enough for per-step use."""

    def __init__(self):
        self._lock = threading.Lock()
        self._run_start = None       # perf_counter at run_start, or None
        self._prior_elapsed = 0.0    # completed runs' wall time
        self._badput_run = {c: 0.0 for c in CAUSES}   # since first run
        self._badput_total = {c: 0.0 for c in CAUSES}  # lifetime
        self._steps = 0
        self._runs = 0

    # ---- run window ------------------------------------------------------
    def run_start(self):
        """Open a training-run window; elapsed time starts counting."""
        if not cfg.enabled:
            return
        with self._lock:
            if self._run_start is None:
                self._run_start = time.perf_counter()
                self._runs += 1

    def run_end(self):
        """Close the run window; the ratio freezes at its final value."""
        if not cfg.enabled:
            return
        with self._lock:
            if self._run_start is not None:
                self._prior_elapsed += time.perf_counter() - self._run_start
                self._run_start = None
        self._update_gauges()

    # ---- signals ---------------------------------------------------------
    def note_step(self, seconds=None):
        """One training step completed (``seconds`` currently informational;
        elapsed comes from the run wall clock)."""
        if not cfg.enabled:
            return
        with self._lock:
            self._steps += 1
            publish = self._steps % 16 == 0   # gauge refresh off hot path
        _registry().counter('goodput.steps',
                            help='fit() steps seen by the goodput '
                                 'ledger').inc()
        if publish:
            self._update_gauges()

    def note_badput(self, cause, seconds):
        """Book ``seconds`` of wall time against ``cause``. Counted toward
        the ratio only while a run window is open."""
        if not cfg.enabled or seconds is None or seconds <= 0:
            return
        if cause not in CAUSES:
            cause = 'requeue'
        with self._lock:
            self._badput_total[cause] += seconds
            if self._run_start is not None:
                self._badput_run[cause] += seconds
        _registry().counter(
            'goodput.badput_ms', {'cause': cause},
            help='badput wall time per cause (ms)').inc(
                round(1e3 * seconds, 3))
        self._update_gauges()

    def note_data_wait(self, seconds):
        """Batch-iterator wait; only the portion of a wait that exceeds
        the stall floor is badput (prefetch-hidden loads are goodput)."""
        if seconds is None:
            return
        floor = _data_floor_s()
        if seconds > floor:
            self.note_badput('data_stall', seconds - floor)

    def badput(self, cause):
        """``with ledger.badput('checkpoint'):`` — measure and book."""
        return _BadputTimer(self, cause)

    def data_iter(self, it):
        """Wrap a batch iterable so every blocking ``next()`` is measured
        into ``data_stall`` (above the floor). Always-on equivalent of
        StepTimer's data phase, feeding the ledger instead."""
        if not cfg.enabled:
            return it

        def gen():
            src = iter(it)
            while True:
                t0 = time.perf_counter()
                try:
                    batch = next(src)
                except StopIteration:
                    return
                self.note_data_wait(time.perf_counter() - t0)
                yield batch
        return gen()

    # ---- views -----------------------------------------------------------
    def _elapsed_locked(self):
        el = self._prior_elapsed
        if self._run_start is not None:
            el += time.perf_counter() - self._run_start
        return el

    def ratio(self):
        """goodput seconds / elapsed run seconds (1.0 before any run)."""
        with self._lock:
            el = self._elapsed_locked()
            bad = sum(self._badput_run.values())
        if el <= 0:
            return 1.0
        return max(0.0, min(1.0, (el - bad) / el))

    def snapshot(self):
        """JSON-able ledger state — the ``/debug/goodput`` body."""
        with self._lock:
            el = self._elapsed_locked()
            bad_run = dict(self._badput_run)
            bad_total = dict(self._badput_total)
            steps = self._steps
            runs = self._runs
            active = self._run_start is not None
        bad = sum(bad_run.values())
        ratio = max(0.0, min(1.0, (el - bad) / el)) if el > 0 else 1.0
        return {'enabled': cfg.enabled, 'run_active': active, 'runs': runs,
                'steps': steps, 'elapsed_s': round(el, 6),
                'goodput_s': round(max(el - bad, 0.0), 6),
                'ratio': round(ratio, 6),
                'badput_s': {c: round(v, 6) for c, v in bad_run.items()},
                'badput_lifetime_s': {c: round(v, 6)
                                      for c, v in bad_total.items()},
                'data_stall_floor_ms': round(1e3 * _data_floor_s(), 3)}

    def _update_gauges(self):
        if not cfg.enabled:
            return
        reg = _registry()
        reg.gauge('goodput.ratio',
                  help='goodput / elapsed wall time of the training '
                       'run').set(round(self.ratio(), 6))
        with self._lock:
            reg.gauge('goodput.elapsed_s').set(
                round(self._elapsed_locked(), 3))

    def reset(self):
        with self._lock:
            self._run_start = None
            self._prior_elapsed = 0.0
            self._badput_run = {c: 0.0 for c in CAUSES}
            self._badput_total = {c: 0.0 for c in CAUSES}
            self._steps = 0
            self._runs = 0


class _BadputTimer:
    __slots__ = ('_ledger', '_cause', '_t0')

    def __init__(self, ledger, cause):
        self._ledger = ledger
        self._cause = cause
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._ledger.note_badput(self._cause,
                                 time.perf_counter() - self._t0)
        return False


_ledger = GoodputLedger()


def ledger():
    """The process-wide ledger (one training process, one ledger)."""
    return _ledger


def note_badput(cause, seconds):
    _ledger.note_badput(cause, seconds)


def snapshot():
    return _ledger.snapshot()


def reset_goodput():
    _ledger.reset()
