"""Process-wide metrics registry: Counter / Gauge / Histogram.

One namespace for every telemetry source in the framework (``train.*``,
``serve.*``, ``fault.*``, ``ckpt.*``, ``data.*``). Metric families are
created on first use and keyed by (name, labels); the same (name, labels)
pair always returns the same child, so independent call sites accumulate
into one series — the TPP (arxiv 2104.05755) discipline of uniform
primitive-level instrumentation.

Thread-safety: the registry lock guards family/child creation only; each
child carries its own lock for updates, so hot-path increments never
serialize against unrelated metrics.

Disabled mode (``PADDLE_TPU_OBS=0``): the module-level helpers return one
shared no-op singleton — no allocation, no registration, near-zero cost.
Holders that must keep working regardless (StepTimer, ServingStats)
construct private unregistered ``Counter``/``Histogram`` instances instead.
"""
import collections
import json
import os
import threading
import time

DEFAULT_WINDOW = 4096
_QUANTILES = (50, 90, 99)


class _Config:
    __slots__ = ('enabled',)


cfg = _Config()
cfg.enabled = os.environ.get('PADDLE_TPU_OBS', '1').lower() not in (
    '0', 'false', 'off')


def enabled():
    return cfg.enabled


def set_enabled(flag):
    """Runtime toggle (tests, embedding apps). The env knob
    ``PADDLE_TPU_OBS=0`` sets the initial value at import."""
    cfg.enabled = bool(flag)


def percentile(samples, q):
    """Nearest-rank percentile of an (unsorted) sample sequence.

    The ONE percentile implementation in the framework — StepTimer, the
    serving stats, and the registry histograms all report latency through
    it. Returns ``None`` for an empty sequence (callers decide how to
    render "no data"); a single sample is every percentile of itself; q is
    clamped into [0, 100] instead of wrapping around via negative indexing.
    """
    n = len(samples)
    if n == 0:
        return None
    s = sorted(samples)
    if q <= 0:
        return s[0]
    if q >= 100:
        return s[-1]
    return s[min(n - 1, int(n * q / 100.0))]


def fmt_key(name, labels=None):
    """Canonical snapshot key: ``name`` or ``name{k=v,...}`` (sorted)."""
    if not labels:
        return name
    inner = ','.join(f'{k}={labels[k]}' for k in sorted(labels))
    return f'{name}{{{inner}}}'


class Counter:
    """Monotonic counter. ``inc`` only; ``reset`` exists for view-holders
    (StepTimer/ServingStats) that own their series' lifetime."""

    __slots__ = ('name', 'labels', '_lock', '_value')

    def __init__(self, name='', labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0

    @property
    def key(self):
        return fmt_key(self.name, self.labels)


class Gauge:
    """Point-in-time value (queue depth, circuit state, last loss)."""

    __slots__ = ('name', 'labels', '_lock', '_value')

    def __init__(self, name='', labels=None):
        self.name = name
        self.labels = dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0.0

    @property
    def key(self):
        return fmt_key(self.name, self.labels)


class Histogram:
    """Windowed sample histogram with nearest-rank percentiles.

    ``count``/``sum``/``min``/``max`` cover the full lifetime; percentiles
    come from a bounded window (last ``window`` observations) so a
    long-lived process never grows telemetry without bound — the same
    policy the serving stats have used since PR 3.
    """

    __slots__ = ('name', 'labels', 'window', '_lock', '_samples', '_count',
                 '_sum', '_min', '_max')

    def __init__(self, name='', labels=None, window=DEFAULT_WINDOW):
        self.name = name
        self.labels = dict(labels or {})
        self.window = window
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self._samples = collections.deque(maxlen=self.window)
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._samples.append(v)
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def percentile(self, q):
        with self._lock:
            return percentile(self._samples, q)

    def since(self, count):
        """Samples observed after lifetime-count ``count`` (capped at the
        window). Returns ``(current_count, new_samples)`` — the delta-window
        primitive the SLO watcher evaluates percentiles over, so a breached
        rule can resolve as soon as fresh traffic is healthy instead of
        waiting for the full window to cycle."""
        with self._lock:
            new = self._count - count
            if new <= 0:
                return self._count, []
            s = list(self._samples)
            take = min(new, len(s))
            return self._count, s[len(s) - take:]

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    @property
    def mean(self):
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def stats(self):
        with self._lock:
            out = {'count': self._count,
                   'sum': round(self._sum, 6),
                   'mean': round(self._sum / self._count, 6)
                   if self._count else 0.0,
                   'min': self._min, 'max': self._max}
            for q in _QUANTILES:
                out[f'p{q}'] = percentile(self._samples, q)
        return out

    @property
    def key(self):
        return fmt_key(self.name, self.labels)


class _NullMetric:
    """Shared no-op standing in for every metric type when observability is
    disabled — the zero-allocation fast path (one process-wide instance)."""

    __slots__ = ()
    value = 0
    count = 0
    sum = 0.0
    mean = 0.0
    name = ''
    labels = {}
    key = ''

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    def reset(self):
        pass

    def percentile(self, q):
        return None

    def since(self, count):
        return 0, []

    def stats(self):
        return {'count': 0, 'sum': 0.0, 'mean': 0.0, 'min': None,
                'max': None, 'p50': None, 'p90': None, 'p99': None}


NULL_METRIC = _NullMetric()

_TYPES = {'counter': Counter, 'gauge': Gauge, 'histogram': Histogram}


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._families = {}     # name -> [type_name, {label_key: child}]
        self._help = {}         # name -> help text (family-level)

    def _child(self, type_name, name, labels, help=None, **kwargs):
        lk = tuple(sorted((labels or {}).items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = (type_name, {})
                self._families[name] = fam
                # help is registered at family creation; the default is
                # the metric name so strict scrapers always see a # HELP
                self._help[name] = str(help) if help else name
            elif fam[0] != type_name:
                raise ValueError(
                    f'metric {name!r} already registered as {fam[0]}, '
                    f'requested as {type_name}')
            elif help:
                # a later call site that DOES know the semantics upgrades
                # a default (name-only) help; explicit text never churns
                if self._help.get(name) in (None, name):
                    self._help[name] = str(help)
            child = fam[1].get(lk)
            if child is None:
                child = _TYPES[type_name](name, labels, **kwargs)
                fam[1][lk] = child
            return child

    def counter(self, name, labels=None, help=None):
        return self._child('counter', name, labels, help=help)

    def gauge(self, name, labels=None, help=None):
        return self._child('gauge', name, labels, help=help)

    def histogram(self, name, labels=None, window=DEFAULT_WINDOW,
                  help=None):
        return self._child('histogram', name, labels, help=help,
                           window=window)

    def help_text(self, name):
        """The registered family help (None for unknown families)."""
        with self._lock:
            return self._help.get(name)

    def find(self, name, labels=None):
        """Read-only lookup: the existing child for (name, labels) or
        ``None`` — never creates a family. The SLO watcher polls through
        this so a rule over a series that hasn't reported yet does not
        materialize an empty family in the snapshot."""
        lk = tuple(sorted((labels or {}).items()))
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            return fam[1].get(lk)

    def reset(self):
        with self._lock:
            self._families.clear()
            self._help.clear()

    def _items(self):
        with self._lock:
            return [(name, t, list(children.values()),
                     self._help.get(name, name))
                    for name, (t, children) in sorted(self._families.items())]

    # the registry's own health gauges never count toward cardinality, so
    # back-to-back exports report the same figure (byte-identical renders)
    _SELF_FAMILIES = ('obs.series_total', 'obs.trace_dropped_total')

    def series_total(self):
        """Number of registered series (children across all families,
        excluding the registry's own health gauges) — the
        label-cardinality figure behind ``obs.series_total``."""
        with self._lock:
            return sum(len(children)
                       for name, (_, children) in self._families.items()
                       if name not in self._SELF_FAMILIES)

    def refresh_self_metrics(self):
        """Refresh the registry's own health gauges: ``obs.series_total``
        (cardinality-explosion detector) and ``obs.trace_dropped_total``
        (span-ring overflow). Called on every export (snapshot /
        exposition) so SLO rules and scrapers always see current values;
        safe to call directly. The trace import is deferred — trace.py
        imports this module at load time."""
        if not cfg.enabled:
            return
        n = self.series_total()
        if n == 0:
            # an empty registry must stay empty through an export — don't
            # let observing the registry materialize its first series
            return
        from .trace import trace_dropped
        self.gauge('obs.series_total',
                   help='registered metric series (children across all '
                        'families)').set(n)
        self.gauge('obs.trace_dropped_total',
                   help='span-ring events evicted by the bounded trace '
                        'buffer').set(trace_dropped())

    def snapshot(self):
        """JSON-serializable view of every registered series."""
        self.refresh_self_metrics()
        out = {'ts': time.time(),
               'counters': {}, 'gauges': {}, 'histograms': {}}
        for name, t, children, _ in self._items():
            section = out[t + 's']
            for c in children:
                section[c.key] = c.stats() if t == 'histogram' else c.value
        return out

    def to_prometheus(self):
        """Prometheus text exposition format (histograms as summaries),
        with ``# HELP`` alongside every ``# TYPE`` so the exposition
        survives strict scrapers when federated."""
        self.refresh_self_metrics()
        lines = []
        for name, t, children, help_text in self._items():
            pname = _prom_name(name)
            lines.append(f'# HELP {pname} {_prom_help(help_text)}')
            lines.append(f'# TYPE {pname} '
                         f'{"summary" if t == "histogram" else t}')
            for c in children:
                lbl = _prom_labels(c.labels)
                if t == 'histogram':
                    st = c.stats()
                    for q in _QUANTILES:
                        v = st[f'p{q}']
                        if v is None:
                            continue
                        ql = _prom_labels(dict(c.labels,
                                               quantile=str(q / 100.0)))
                        lines.append(f'{pname}{ql} {v}')
                    lines.append(f'{pname}_sum{lbl} {st["sum"]}')
                    lines.append(f'{pname}_count{lbl} {st["count"]}')
                else:
                    lines.append(f'{pname}{lbl} {c.value}')
        return '\n'.join(lines) + ('\n' if lines else '')


def _prom_name(name):
    return ''.join(ch if (ch.isalnum() or ch in '_:') else '_'
                   for ch in name)


def _prom_help(text):
    # exposition-format HELP escaping: backslash and newline only
    return str(text).replace('\\', '\\\\').replace('\n', '\\n')


def _prom_labels(labels):
    if not labels:
        return ''
    parts = []
    for k, v in sorted(labels.items()):
        val = (str(v).replace('\\', '\\\\').replace('"', '\\"')
               .replace('\n', '\\n'))
        parts.append(f'{_prom_name(str(k))}="{val}"')
    return '{' + ','.join(parts) + '}'


_default = MetricsRegistry()


def registry():
    """The process-wide default registry."""
    return _default


def counter(name, labels=None, help=None):
    if not cfg.enabled:
        return NULL_METRIC
    return _default.counter(name, labels, help=help)


def gauge(name, labels=None, help=None):
    if not cfg.enabled:
        return NULL_METRIC
    return _default.gauge(name, labels, help=help)


def histogram(name, labels=None, window=DEFAULT_WINDOW, help=None):
    if not cfg.enabled:
        return NULL_METRIC
    return _default.histogram(name, labels, window=window, help=help)


def find(name, labels=None):
    if not cfg.enabled:
        return None
    return _default.find(name, labels)


def snapshot():
    return _default.snapshot()


def to_prometheus():
    return _default.to_prometheus()


def dump_snapshot(path):
    snap = _default.snapshot()
    with open(path, 'w') as f:
        json.dump(snap, f, indent=1, sort_keys=True, default=str)
    return snap
