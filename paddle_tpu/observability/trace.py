"""Structured span tracer with Chrome-trace/Perfetto JSON export.

``span("train.step", step=n)`` is a context manager that records wall and
monotonic timing for the enclosed region and, when the platform provides
it, forwards the region to ``jax.profiler.TraceAnnotation`` so spans also
show up inside TensorBoard/XProf device traces (the arxiv 2108.11076
pattern: host-side structure made legible next to TPU utilization).

Completed spans land in a bounded process-wide ring buffer as Chrome
trace-event dicts (``ph: 'X'`` complete events; ``event()`` emits
``ph: 'i'`` instants). ``dump_trace(path)`` writes a file that loads
directly in ``chrome://tracing`` / Perfetto. Nesting needs no explicit
parent tracking — complete events on the same tid nest by ts/dur.

When observability is disabled, ``span()`` returns one shared no-op
singleton: no allocation, no timestamps, no buffer writes.
"""
import collections
import json
import os
import threading
import time

from .registry import cfg

TRACE_CAP = int(os.environ.get('PADDLE_TPU_OBS_TRACE_CAP', '100000'))

_lock = threading.Lock()
_events = collections.deque(maxlen=TRACE_CAP)
_dropped = 0            # events evicted by a full ring (or a cap shrink)


def set_trace_cap(n):
    """Re-bound the span ring at runtime (tests, the ``/debug/trace``
    endpoint). The env knob only sets the import-time default; this swaps
    the ring for one of the new capacity, keeping the newest events.
    Returns the new cap."""
    global TRACE_CAP, _events, _dropped
    n = max(1, int(n))
    with _lock:
        TRACE_CAP = n
        _dropped += max(0, len(_events) - n)
        _events = collections.deque(_events, maxlen=n)
    return n


def trace_cap():
    return TRACE_CAP


def trace_dropped():
    """Lifetime count of events the bounded ring has evicted — surfaced
    as the ``obs.trace_dropped_total`` registry gauge so ring overflow is
    itself observable (and SLO-rule-able)."""
    with _lock:
        return _dropped


def _append_locked(rec):
    # caller holds _lock; eviction by a full deque is the silent-drop
    # path the self-metrics satellite makes visible
    global _dropped
    if len(_events) == _events.maxlen:
        _dropped += 1
    _events.append(rec)
_tid_names = {}          # tid -> thread name at record time (for ph:'M')
_origin_mono = time.perf_counter()
_origin_wall = time.time()

_jax_profiler_mod = None
_jax_profiler_checked = False


def _jax_profiler():
    """jax.profiler if importable, else None (cached). The TraceAnnotation
    attribute is looked up per use so platform stubs (and tests) that
    remove or break it degrade the span to host-only timing."""
    global _jax_profiler_mod, _jax_profiler_checked
    if not _jax_profiler_checked:
        try:
            from jax import profiler as _p
            _jax_profiler_mod = _p
        except Exception:
            _jax_profiler_mod = None
        _jax_profiler_checked = True
    return _jax_profiler_mod


def _now_us():
    return (time.perf_counter() - _origin_mono) * 1e6


class Span:
    """One timed region. Use via ``observability.span(name, **attrs)``."""

    __slots__ = ('name', 'attrs', 'duration', 'wall_start', '_ts', '_ann')

    def __init__(self, name, attrs=None):
        self.name = name
        self.attrs = attrs or None
        self.duration = 0.0          # monotonic seconds, set on exit
        self.wall_start = 0.0
        self._ts = 0.0
        self._ann = None

    def __enter__(self):
        mod = _jax_profiler()
        if mod is not None:
            try:
                ann = mod.TraceAnnotation(self.name)
                ann.__enter__()
                self._ann = ann
            except Exception:
                self._ann = None
        self.wall_start = time.time()
        self._ts = _now_us()
        return self

    def event(self, name, **attrs):
        """Instant event stamped inside this span's thread/timeline."""
        record_event(name, **attrs)

    def __exit__(self, etype, evalue, tb):
        end = _now_us()
        self.duration = (end - self._ts) / 1e6
        if self._ann is not None:
            try:
                self._ann.__exit__(None, None, None)
            except Exception:
                pass
            self._ann = None
        args = dict(self.attrs) if self.attrs else {}
        if etype is not None:
            args['error'] = f'{etype.__name__}: {evalue}'[:200]
        tid = threading.get_ident()
        rec = {'name': self.name, 'ph': 'X', 'cat': self.name.split('.')[0],
               'ts': round(self._ts, 3), 'dur': round(end - self._ts, 3),
               'pid': os.getpid(), 'tid': tid}
        if args:
            rec['args'] = args
        with _lock:
            _append_locked(rec)
            _tid_names[tid] = threading.current_thread().name
        return False


class _NullSpan:
    __slots__ = ()
    duration = 0.0
    wall_start = 0.0
    name = ''
    attrs = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def event(self, name, **attrs):
        pass


NULL_SPAN = _NullSpan()


def span(name, **attrs):
    """``with span('serve.batch', bucket=8):`` — returns the no-op singleton
    when observability is disabled."""
    if not cfg.enabled:
        return NULL_SPAN
    return Span(name, attrs)


def record_event(name, **attrs):
    """Standalone instant event (``ph: 'i'``) — fault injections, retries,
    circuit transitions."""
    if not cfg.enabled:
        return
    tid = threading.get_ident()
    rec = {'name': name, 'ph': 'i', 'cat': name.split('.')[0], 's': 't',
           'ts': round(_now_us(), 3), 'pid': os.getpid(), 'tid': tid}
    if attrs:
        rec['args'] = attrs
    with _lock:
        _append_locked(rec)
        _tid_names[tid] = threading.current_thread().name


def now_us():
    """Current trace-clock timestamp (µs since the monotonic origin) —
    the same clock every event's ``ts`` is stamped in."""
    return _now_us()


def trace_events(since_us=None):
    """Copy of the completed-event ring (Chrome trace-event dicts).
    ``since_us`` keeps only events whose ``ts`` is at or after that
    trace-clock timestamp (the ``/debug/trace?ms=N`` capture window)."""
    with _lock:
        events = list(_events)
    if since_us is not None:
        events = [e for e in events if e.get('ts', 0.0) >= since_us]
    return events


def reset_trace():
    global _dropped
    with _lock:
        _events.clear()
        _tid_names.clear()
        _dropped = 0


def _wall_anchor():
    """Fresh wall↔monotonic mapping, taken NOW. The import-time pair
    drifts in long runs (NTP slew, clock steps, VM suspend), so dumped
    wall timestamps derived from it go stale; re-deriving the origin from
    a current reading of both clocks keeps ``wall_origin + ts/1e6`` true
    to real time at dump time. Both clocks (and the measured drift) land
    in the metadata so consumers can pick either."""
    mono_now = time.perf_counter()
    wall_now = time.time()
    wall_origin = wall_now - (mono_now - _origin_mono)
    return {'wall_origin': wall_origin,
            'wall_origin_at_import': _origin_wall,
            'wall_at_dump': wall_now,
            'mono_us_at_dump': round((mono_now - _origin_mono) * 1e6, 3),
            'wall_drift_s': round(wall_origin - _origin_wall, 6),
            'clock': 'perf_counter_us_since_origin'}


def build_trace_doc(events=None):
    """Chrome-trace document for ``events`` (default: the whole ring),
    with process/thread-name metadata (``ph:'M'``) and the re-anchored
    wall-clock mapping in ``otherData``."""
    with _lock:
        if events is None:
            events = list(_events)
        tid_names = dict(_tid_names)
    pid = os.getpid()
    meta = [{'name': 'process_name', 'ph': 'M', 'pid': pid,
             'args': {'name': 'paddle_tpu'}}]
    seen_tids = {e['tid'] for e in events if 'tid' in e}
    for tid, tname in sorted(tid_names.items()):
        if tid in seen_tids:
            meta.append({'name': 'thread_name', 'ph': 'M', 'pid': pid,
                         'tid': tid, 'args': {'name': tname}})
    return {'traceEvents': meta + events,
            'displayTimeUnit': 'ms',
            'otherData': _wall_anchor()}


def dump_trace(path):
    """Write the span ring as Chrome-trace JSON (loads in chrome://tracing
    and Perfetto). Returns the event count written. Metadata (``ph:'M'``)
    events name the process and every thread that recorded a span, so
    Perfetto lanes read "Thread-dispatch" instead of a bare TID."""
    doc = build_trace_doc()
    n = sum(1 for e in doc['traceEvents'] if e.get('ph') != 'M')
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, 'w') as f:
        json.dump(doc, f, default=str)
    return n
