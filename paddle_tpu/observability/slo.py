"""Declarative SLO rules over registry series, with debounce + callbacks.

The machine-readable breach signal the ROADMAP's autoscale-on-queue-wait
design plugs into: a ``Rule`` names a registry series (e.g.
``serve.queue_wait_ms{engine=e0}``), a statistic, a comparison, and a
threshold; a ``Watcher`` evaluates its rules (manually via ``evaluate()``
or on a background thread via ``start()``), tracking an ok → firing →
resolved state machine per rule:

- firing increments ``slo.breaches{rule}``, sets ``slo.firing{rule}`` = 1,
  emits an ``slo.fire`` trace event, and invokes ``on_fire(rule, value)``;
- resolving sets the gauge back to 0, emits ``slo.resolve``, and invokes
  ``on_resolve(rule, value)``. Callback errors are counted
  (``slo.callback_errors{rule}``), never propagated into the poll loop.

Histogram statistics (``p50``/``p90``/``p99``/``mean``) are computed over
the *delta window* — only samples observed since the rule's previous
evaluation — so a breached rule resolves as soon as fresh traffic is
healthy instead of waiting for the 4096-sample window to cycle out.
``rate`` differentiates a counter against wall time. An evaluation with
no new data leaves the rule's state unchanged.

Disabled mode: ``watcher()`` returns ``NULL_WATCHER`` whose methods are
no-ops — no thread, no registry families.
"""
import threading
import time
import weakref

from .registry import cfg, fmt_key, percentile, registry as _registry
from .trace import record_event

# Every live Watcher registers here (weakly — no lifetime coupling) so the
# telemetry server's /debug/slo can enumerate rule states process-wide.
_watchers = weakref.WeakSet()

_CMPS = {
    '>': lambda v, t: v > t,
    '>=': lambda v, t: v >= t,
    '<': lambda v, t: v < t,
    '<=': lambda v, t: v <= t,
}
_STATS = ('value', 'rate', 'mean', 'p50', 'p90', 'p99')


class Rule:
    """One threshold rule. ``debounce`` is the number of *consecutive*
    breaching evaluations required before the rule fires (1 = immediate);
    a single healthy evaluation resolves it."""

    def __init__(self, name, series, threshold, labels=None, stat='value',
                 cmp='>', debounce=1, on_fire=None, on_resolve=None):
        if stat not in _STATS:
            raise ValueError(f'stat {stat!r} not in {_STATS}')
        if cmp not in _CMPS:
            raise ValueError(f'cmp {cmp!r} not in {tuple(_CMPS)}')
        self.name = name
        self.series = series
        self.labels = dict(labels or {})
        self.stat = stat
        self.cmp = cmp
        self.threshold = float(threshold)
        self.debounce = max(1, int(debounce))
        self.on_fire = on_fire
        self.on_resolve = on_resolve
        # evaluation state
        self.state = 'ok'            # 'ok' | 'firing'
        self.last_value = None
        self._breach_streak = 0
        self._hist_count = 0         # histogram delta-window cursor
        self._rate_prev = None       # (value, t) for stat='rate'

    def _sample(self, now):
        """-> (has_data, value) for this evaluation."""
        m = _registry().find(self.series, self.labels)
        if m is None:
            return False, None
        if self.stat == 'value':
            return True, float(m.value if hasattr(m, 'value') else m.count)
        if self.stat == 'rate':
            cur = float(m.value if hasattr(m, 'value') else m.count)
            prev = self._rate_prev
            self._rate_prev = (cur, now)
            if prev is None or now <= prev[1]:
                return False, None
            return True, (cur - prev[0]) / (now - prev[1])
        # histogram stats over the delta window
        if not hasattr(m, 'since'):
            return False, None
        self._hist_count, samples = m.since(self._hist_count)
        if not samples:
            return False, None
        if self.stat == 'mean':
            return True, sum(samples) / len(samples)
        return True, percentile(samples, int(self.stat[1:]))

    def describe(self):
        lbl = fmt_key(self.series, self.labels)
        return (f'{self.name}: {self.stat}({lbl}) {self.cmp} '
                f'{self.threshold}')


class Watcher:
    """Evaluates a set of :class:`Rule` objects. Use ``evaluate()`` from
    your own loop, or ``start()`` for a daemon poll thread."""

    def __init__(self, interval=1.0):
        self.interval = float(interval)
        self._rules = []
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        _watchers.add(self)

    def rule(self, name, series, threshold, **kwargs):
        """Create, register, and return a :class:`Rule`."""
        r = Rule(name, series, threshold, **kwargs)
        return self.add_rule(r)

    def add_rule(self, r):
        with self._lock:
            if any(x.name == r.name for x in self._rules):
                raise ValueError(f'duplicate rule name {r.name!r}')
            self._rules.append(r)
        return r

    def remove_rule(self, name):
        """Deregister a rule by name (a fleet autoscaler retires a
        replica's queue-wait rule when the replica is decommissioned).
        Clears the rule's ``slo.firing`` gauge if it was firing; returns
        the removed Rule or None."""
        with self._lock:
            r = next((x for x in self._rules if x.name == name), None)
            if r is not None:
                self._rules.remove(r)
        if r is not None and r.state == 'firing':
            _registry().gauge('slo.firing', {'rule': r.name}).set(0)
        return r

    @property
    def rules(self):
        with self._lock:
            return list(self._rules)

    def states(self):
        with self._lock:
            return {r.name: r.state for r in self._rules}

    def _callback(self, fn, r, value):
        if fn is None:
            return
        try:
            fn(r, value)
        except Exception:
            _registry().counter('slo.callback_errors', {'rule': r.name}).inc()

    def evaluate(self, now=None):
        """Evaluate every rule once. Returns the list of transitions made:
        ``[(rule_name, 'fire'|'resolve', value), ...]``."""
        if not cfg.enabled:
            return []
        now = time.monotonic() if now is None else now
        transitions = []
        reg = _registry()
        for r in self.rules:
            has_data, value = r._sample(now)
            if not has_data:
                continue
            r.last_value = value
            breached = _CMPS[r.cmp](value, r.threshold)
            if breached:
                r._breach_streak += 1
                if r.state == 'ok' and r._breach_streak >= r.debounce:
                    r.state = 'firing'
                    reg.counter('slo.breaches', {'rule': r.name}).inc()
                    reg.gauge('slo.firing', {'rule': r.name}).set(1)
                    record_event('slo.fire', rule=r.name, value=value,
                                 threshold=r.threshold)
                    self._callback(r.on_fire, r, value)
                    transitions.append((r.name, 'fire', value))
            else:
                r._breach_streak = 0
                if r.state == 'firing':
                    r.state = 'ok'
                    reg.gauge('slo.firing', {'rule': r.name}).set(0)
                    record_event('slo.resolve', rule=r.name, value=value,
                                 threshold=r.threshold)
                    self._callback(r.on_resolve, r, value)
                    transitions.append((r.name, 'resolve', value))
        return transitions

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.evaluate()
            except Exception:
                _registry().counter('slo.eval_errors').inc()

    def start(self):
        """Start the daemon poll thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name='slo-watcher', daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class _NullWatcher:
    """Shared no-op watcher for disabled mode: accepts the full API, never
    creates threads, rules, or registry families."""

    __slots__ = ()
    interval = 0.0
    rules = ()

    def rule(self, name, series, threshold, **kwargs):
        return None

    def add_rule(self, r):
        return r

    def remove_rule(self, name):
        return None

    def states(self):
        return {}

    def evaluate(self, now=None):
        return []

    def start(self):
        return self

    def stop(self, timeout=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_WATCHER = _NullWatcher()


def rule_states():
    """Every rule of every live Watcher as a JSON-able list (the
    ``/debug/slo`` payload): name, ok/firing state, last value, and the
    rule's full description. Empty when disabled or no watchers exist."""
    if not cfg.enabled:
        return []
    out = []
    for w in list(_watchers):
        polling = w._thread is not None and w._thread.is_alive()
        for r in w.rules:
            out.append({'rule': r.name, 'state': r.state,
                        'series': fmt_key(r.series, r.labels),
                        'stat': r.stat, 'cmp': r.cmp,
                        'threshold': r.threshold,
                        'last_value': r.last_value,
                        'debounce': r.debounce,
                        'polling': polling,
                        'describe': r.describe()})
    return sorted(out, key=lambda d: d['rule'])


def watcher(interval=1.0):
    """Factory honoring disabled mode — the supported entry point."""
    if not cfg.enabled:
        return NULL_WATCHER
    return Watcher(interval)
