"""paddle.text parity: NLP datasets."""
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
    ViterbiDecoder, viterbi_decode)
