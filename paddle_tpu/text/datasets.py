"""Text datasets. Reference: python/paddle/text/datasets/*.

Offline: each dataset reads the reference's file formats from
$PADDLE_TPU_DATA_HOME when present, else generates deterministic synthetic
corpora with the same item structure, so pipelines run without egress.
"""
import os
import tarfile

import numpy as np

from ..io import Dataset

DATA_HOME = os.path.expanduser(os.environ.get('PADDLE_TPU_DATA_HOME',
                                              '~/.cache/paddle_tpu/datasets'))


class Imdb(Dataset):
    """Sentiment classification: (word-id sequence, 0/1 label)."""

    def __init__(self, data_file=None, mode='train', cutoff=150, download=True):
        data_file = data_file or os.path.join(DATA_HOME, 'imdb', 'aclImdb_v1.tar.gz')
        self.word_idx = {}
        self.docs, self.labels = [], []
        if os.path.exists(data_file):
            self._load_tar(data_file, mode, cutoff)
        else:
            rng = np.random.RandomState(0 if mode == 'train' else 1)
            vocab = 500
            self.word_idx = {f'w{i}': i for i in range(vocab)}
            n = 512 if mode == 'train' else 128
            for i in range(n):
                ln = rng.randint(5, 60)
                self.docs.append(rng.randint(0, vocab, ln).tolist())
                self.labels.append(int(rng.rand() > 0.5))

    def _load_tar(self, path, mode, cutoff):
        import re
        import collections
        pos_pat = re.compile(rf'aclImdb/{mode}/pos/.*\.txt$')
        neg_pat = re.compile(rf'aclImdb/{mode}/neg/.*\.txt$')
        tokenize = re.compile(r'[a-z]+').findall
        freq = collections.Counter()
        texts = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                label = 1 if pos_pat.match(m.name) else \
                    (0 if neg_pat.match(m.name) else None)
                if label is None:
                    continue
                words = tokenize(tf.extractfile(m).read().decode().lower())
                freq.update(words)
                texts.append((words, label))
        vocab = [w for w, _ in freq.most_common(cutoff)]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        for words, label in texts:
            self.docs.append([self.word_idx.get(w, unk) for w in words])
            self.labels.append(label)

    def __getitem__(self, idx):
        return (np.asarray(self.docs[idx], 'int64'),
                np.asarray(self.labels[idx], 'int64'))

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset."""

    def __init__(self, data_file=None, data_type='NGRAM', window_size=5,
                 mode='train', min_word_freq=50, download=True):
        self.window_size = window_size
        rng = np.random.RandomState(2 if mode == 'train' else 3)
        vocab = 300
        self.word_idx = {f'w{i}': i for i in range(vocab)}
        n = 2048 if mode == 'train' else 256
        stream = rng.randint(0, vocab, n + window_size)
        self.samples = [stream[i:i + window_size].astype('int64')
                        for i in range(n)]

    def __getitem__(self, idx):
        s = self.samples[idx]
        return tuple(np.asarray(x, 'int64') for x in s)

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    def __init__(self, data_file=None, mode='train', test_ratio=0.1,
                 rand_seed=0, download=True):
        rng = np.random.RandomState(rand_seed)
        n = 1024 if mode == 'train' else 128
        self.rows = [(rng.randint(1, 943), rng.randint(0, 2), rng.randint(1, 50),
                      rng.randint(1, 1682), rng.randint(0, 19),
                      float(rng.randint(1, 6))) for _ in range(n)]

    def __getitem__(self, idx):
        u, g, a, m, c, r = self.rows[idx]
        return (np.asarray(u, 'int64'), np.asarray(g, 'int64'),
                np.asarray(a, 'int64'), np.asarray(m, 'int64'),
                np.asarray(c, 'int64'), np.asarray(r, 'float32'))

    def __len__(self):
        return len(self.rows)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode='train', download=True):
        data_file = data_file or os.path.join(DATA_HOME, 'uci_housing',
                                              'housing.data')
        if os.path.exists(data_file):
            data = np.loadtxt(data_file).astype('float32')
        else:
            rng = np.random.RandomState(4)
            X = rng.rand(506, 13).astype('float32')
            w = rng.rand(13).astype('float32')
            y = (X @ w * 10 + rng.randn(506).astype('float32'))[:, None]
            data = np.concatenate([X, y], axis=1)
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == 'train' else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class _SyntheticTranslation(Dataset):
    SRC_VOCAB = 200
    TRG_VOCAB = 220

    def __init__(self, mode='train', seed=5):
        rng = np.random.RandomState(seed if mode == 'train' else seed + 1)
        n = 512 if mode == 'train' else 64
        self.pairs = []
        for _ in range(n):
            ln = rng.randint(3, 20)
            src = rng.randint(3, self.SRC_VOCAB, ln)
            trg = rng.randint(3, self.TRG_VOCAB, ln + rng.randint(-2, 3))
            self.pairs.append((src, trg))
        self.src_word_idx = {f's{i}': i for i in range(self.SRC_VOCAB)}
        self.trg_word_idx = {f't{i}': i for i in range(self.TRG_VOCAB)}

    def __getitem__(self, idx):
        src, trg = self.pairs[idx]
        trg_in = np.concatenate([[1], trg]).astype('int64')
        trg_out = np.concatenate([trg, [2]]).astype('int64')
        return src.astype('int64'), trg_in, trg_out

    def __len__(self):
        return len(self.pairs)


class WMT14(_SyntheticTranslation):
    def __init__(self, data_file=None, mode='train', dict_size=30000,
                 download=True):
        super().__init__(mode, seed=6)


class WMT16(_SyntheticTranslation):
    def __init__(self, data_file=None, mode='train', src_dict_size=30000,
                 trg_dict_size=30000, lang='en', download=True):
        super().__init__(mode, seed=7)


class Conll05st(Dataset):
    """SRL dataset: (pred, mark, word seq, label seq)."""

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, mode='train',
                 download=True):
        rng = np.random.RandomState(8)
        n = 256
        self.samples = []
        for _ in range(n):
            ln = rng.randint(5, 30)
            words = rng.randint(0, 300, ln).astype('int64')
            pred = rng.randint(0, 50, ln).astype('int64')
            labels = rng.randint(0, 20, ln).astype('int64')
            self.samples.append((words, pred, labels))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Viterbi decoding via lax.scan. Returns (scores, paths)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    pot = potentials._value if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    trans = transition_params._value if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    B, T, N = pot.shape

    def step(carry, emit):
        score = carry                                   # [B, N]
        cand = score[:, :, None] + trans[None]          # [B, N, N]
        best = jnp.max(cand, axis=1) + emit
        idx = jnp.argmax(cand, axis=1)
        return best, idx

    score0 = pot[:, 0]
    scores, idxs = jax.lax.scan(step, score0, jnp.moveaxis(pot[:, 1:], 1, 0))
    final_best = jnp.argmax(scores, axis=-1)

    def backtrack(carry, idx_t):
        cur = carry
        prev = jnp.take_along_axis(idx_t, cur[:, None], axis=1)[:, 0]
        return prev, cur

    _, path_rev = jax.lax.scan(backtrack, final_best, idxs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1),
                             final_best[:, None]], axis=1)
    return Tensor(jnp.max(scores, -1)), Tensor(paths.astype(jnp.int64))


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include)
