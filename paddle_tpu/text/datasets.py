"""Text datasets. Reference: python/paddle/text/datasets/*.

Offline: each dataset reads the reference's file formats from
$PADDLE_TPU_DATA_HOME when present, else generates deterministic synthetic
corpora with the same item structure, so pipelines run without egress.
"""
import os
import tarfile

import numpy as np

from ..io import Dataset

DATA_HOME = os.path.expanduser(os.environ.get('PADDLE_TPU_DATA_HOME',
                                              '~/.cache/paddle_tpu/datasets'))


class Imdb(Dataset):
    """Sentiment classification: (word-id sequence, 0/1 label)."""

    def __init__(self, data_file=None, mode='train', cutoff=150, download=True):
        data_file = data_file or os.path.join(DATA_HOME, 'imdb', 'aclImdb_v1.tar.gz')
        self.word_idx = {}
        self.docs, self.labels = [], []
        if os.path.exists(data_file):
            self._load_tar(data_file, mode, cutoff)
        else:
            rng = np.random.RandomState(0 if mode == 'train' else 1)
            vocab = 500
            self.word_idx = {f'w{i}': i for i in range(vocab)}
            n = 512 if mode == 'train' else 128
            for i in range(n):
                ln = rng.randint(5, 60)
                self.docs.append(rng.randint(0, vocab, ln).tolist())
                self.labels.append(int(rng.rand() > 0.5))

    def _load_tar(self, path, mode, cutoff):
        import re
        import collections
        pos_pat = re.compile(rf'aclImdb/{mode}/pos/.*\.txt$')
        neg_pat = re.compile(rf'aclImdb/{mode}/neg/.*\.txt$')
        tokenize = re.compile(r'[a-z]+').findall
        freq = collections.Counter()
        texts = []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                label = 1 if pos_pat.match(m.name) else \
                    (0 if neg_pat.match(m.name) else None)
                if label is None:
                    continue
                words = tokenize(tf.extractfile(m).read().decode().lower())
                freq.update(words)
                texts.append((words, label))
        vocab = [w for w, _ in freq.most_common(cutoff)]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        unk = len(self.word_idx)
        for words, label in texts:
            self.docs.append([self.word_idx.get(w, unk) for w in words])
            self.labels.append(label)

    def __getitem__(self, idx):
        return (np.asarray(self.docs[idx], 'int64'),
                np.asarray(self.labels[idx], 'int64'))

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset.

    Real files: the reference's simple-examples.tgz with
    ./simple-examples/data/ptb.{train,valid,test}.txt (reference:
    python/paddle/text/datasets/imikolov.py). Word dict built from
    train+valid with min_word_freq cutoff, '<unk>' last.
    """

    def __init__(self, data_file=None, data_type='NGRAM', window_size=5,
                 mode='train', min_word_freq=50, download=True):
        self.window_size = window_size
        self.data_type = data_type
        data_file = data_file or os.path.join(DATA_HOME, 'imikolov',
                                              'simple-examples.tgz')
        if os.path.exists(data_file):
            self._load_tar(data_file, mode, min_word_freq)
        else:
            rng = np.random.RandomState(2 if mode == 'train' else 3)
            vocab = 300
            self.word_idx = {f'w{i}': i for i in range(vocab)}
            self.word_idx['<s>'] = 0
            self.word_idx['<e>'] = 1
            n = 2048 if mode == 'train' else 256
            if data_type == 'SEQ':
                self.samples = []
                for _ in range(n // 8):
                    ln = rng.randint(3, 20)
                    ids = rng.randint(3, vocab, ln)
                    self.samples.append(
                        (np.concatenate([[0], ids]).astype('int64'),
                         np.concatenate([ids, [1]]).astype('int64')))
            else:
                stream = rng.randint(0, vocab, n + window_size)
                self.samples = [tuple(stream[i:i + window_size].tolist())
                                for i in range(n)]

    def _load_tar(self, path, mode, min_word_freq):
        import collections
        base = './simple-examples/data/ptb.{}.txt'
        freq = collections.Counter()
        with tarfile.open(path) as tf:
            for part in ('train', 'valid'):
                for line in tf.extractfile(base.format(part)):
                    words = line.decode().strip().split()
                    freq.update(words)
                    freq.update(('<s>', '<e>'))
            freq.pop('<unk>', None)
            kept = sorted(((w, c) for w, c in freq.items()
                           if c > min_word_freq), key=lambda x: (-x[1], x[0]))
            self.word_idx = {w: i for i, (w, _) in enumerate(kept)}
            self.word_idx['<unk>'] = len(kept)
            unk = self.word_idx['<unk>']
            fname = base.format('valid' if mode in ('valid', 'test') else mode)
            self.samples = []
            for line in tf.extractfile(fname):
                toks = line.decode().strip().split()
                if self.data_type == 'NGRAM':
                    seq = ['<s>'] + toks + ['<e>']
                    if len(seq) < self.window_size:
                        continue
                    ids = [self.word_idx.get(w, unk) for w in seq]
                    for i in range(self.window_size, len(ids) + 1):
                        self.samples.append(tuple(ids[i - self.window_size:i]))
                else:   # SEQ
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    src = [self.word_idx['<s>']] + ids
                    trg = ids + [self.word_idx['<e>']]
                    self.samples.append((np.asarray(src, 'int64'),
                                         np.asarray(trg, 'int64')))

    def __getitem__(self, idx):
        s = self.samples[idx]
        if self.data_type == 'SEQ' and isinstance(s[0], np.ndarray):
            return s
        return tuple(np.asarray(x, 'int64') for x in s)

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens-1M rating prediction.

    Real file: ml-1m.zip with '::'-separated movies/users/ratings .dat files
    (reference: python/paddle/text/datasets/movielens.py). Items follow the
    reference layout: (uid, gender, age_idx, job, mov_id, category_ids,
    title_word_ids, rating) with rating rescaled to [-3, 5] via r*2-5.
    """

    def __init__(self, data_file=None, mode='train', test_ratio=0.1,
                 rand_seed=0, download=True):
        data_file = data_file or os.path.join(DATA_HOME, 'movielens',
                                              'ml-1m.zip')
        if os.path.exists(data_file):
            self._load_zip(data_file, mode, test_ratio, rand_seed)
        else:
            rng = np.random.RandomState(rand_seed)
            n = 1024 if mode == 'train' else 128
            self.rows = [([rng.randint(1, 943)], [rng.randint(0, 2)],
                          [rng.randint(1, 8)], [rng.randint(0, 21)],
                          [rng.randint(1, 1682)],
                          rng.randint(0, 19, rng.randint(1, 4)).tolist(),
                          rng.randint(0, 100, rng.randint(1, 6)).tolist(),
                          [float(rng.randint(1, 6)) * 2 - 5.0])
                         for _ in range(n)]

    def _load_zip(self, path, mode, test_ratio, rand_seed):
        import re
        import zipfile
        title_pat = re.compile(r'(.*)\s*\((\d+)\)\s*$')
        movies, users = {}, {}
        cat_dict, title_dict = {}, {}
        with zipfile.ZipFile(path) as z:
            with z.open('ml-1m/movies.dat') as f:
                for line in f:
                    mid, title, cats = \
                        line.decode('latin1').strip().split('::')
                    m = title_pat.match(title)
                    title = m.group(1) if m else title
                    for c in cats.split('|'):
                        cat_dict.setdefault(c, len(cat_dict))
                    for w in title.lower().split():
                        title_dict.setdefault(w, len(title_dict))
                    movies[int(mid)] = (int(mid), cats.split('|'),
                                        title.lower().split())
            age_idx = {}
            with z.open('ml-1m/users.dat') as f:
                for line in f:
                    uid, gender, age, job, _ = \
                        line.decode('latin1').strip().split('::')
                    age_idx.setdefault(int(age), len(age_idx))
                    users[int(uid)] = (int(uid), 0 if gender == 'M' else 1,
                                       age_idx[int(age)], int(job))
            rng = np.random.RandomState(rand_seed)
            is_test = mode == 'test'
            self.rows = []
            with z.open('ml-1m/ratings.dat') as f:
                for line in f:
                    if (rng.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, r, _ = \
                        line.decode('latin1').strip().split('::')
                    u = users[int(uid)]
                    m = movies[int(mid)]
                    self.rows.append(
                        ([u[0]], [u[1]], [u[2]], [u[3]], [m[0]],
                         [cat_dict[c] for c in m[1]],
                         [title_dict[w] for w in m[2]],
                         [float(r) * 2 - 5.0]))

    def __getitem__(self, idx):
        row = self.rows[idx]
        return tuple(np.asarray(x, 'float32' if i == 7 else 'int64')
                     for i, x in enumerate(row))

    def __len__(self):
        return len(self.rows)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode='train', download=True):
        data_file = data_file or os.path.join(DATA_HOME, 'uci_housing',
                                              'housing.data')
        if os.path.exists(data_file):
            data = np.loadtxt(data_file).astype('float32')
        else:
            rng = np.random.RandomState(4)
            X = rng.rand(506, 13).astype('float32')
            w = rng.rand(13).astype('float32')
            y = (X @ w * 10 + rng.randn(506).astype('float32'))[:, None]
            data = np.concatenate([X, y], axis=1)
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == 'train' else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class _SyntheticTranslation(Dataset):
    SRC_VOCAB = 200
    TRG_VOCAB = 220

    def __init__(self, mode='train', seed=5):
        rng = np.random.RandomState(seed if mode == 'train' else seed + 1)
        n = 512 if mode == 'train' else 64
        self.pairs = []
        for _ in range(n):
            ln = rng.randint(3, 20)
            src = rng.randint(3, self.SRC_VOCAB, ln)
            trg = rng.randint(3, self.TRG_VOCAB, ln + rng.randint(-2, 3))
            self.pairs.append((src, trg))
        self.src_word_idx = {f's{i}': i for i in range(self.SRC_VOCAB)}
        self.trg_word_idx = {f't{i}': i for i in range(self.TRG_VOCAB)}

    def __getitem__(self, idx):
        src, trg = self.pairs[idx]
        trg_in = np.concatenate([[1], trg]).astype('int64')
        trg_out = np.concatenate([trg, [2]]).astype('int64')
        return src.astype('int64'), trg_in, trg_out

    def __len__(self):
        return len(self.pairs)


def _load_wmt_tar(path, mode, src_dict_name, trg_dict_name, data_name,
                  src_dict_size, trg_dict_size=None, max_len=80):
    """Shared WMT tar parsing: *.dict members (one word per line, index =
    line number) + tab-separated parallel corpus members. Reference:
    python/paddle/text/datasets/wmt14.py _load_data."""
    import re
    if trg_dict_size is None:
        trg_dict_size = src_dict_size
    UNK, START, END = 2, '<s>', '<e>'
    pairs = []
    with tarfile.open(path) as tf:
        names = [m.name for m in tf.getmembers()]

        def find(suffix):
            # accept both 'src.dict' and size-suffixed 'en_30000.dict' layouts
            stem = suffix[:-len('.dict')] if suffix.endswith('.dict') else None
            pat = re.compile(r'(^|/)' + re.escape(stem) + r'(_\d+)?\.dict$') \
                if stem else None
            for n in names:
                if n.endswith(suffix) or (pat and pat.search(n)):
                    return n
            return None

        def to_dict(name, size):
            d = {}
            for i, line in enumerate(tf.extractfile(name)):
                if size > 0 and i >= size:
                    break
                d[line.decode('utf-8', 'replace').strip()] = i
            return d

        src_name, trg_name, data_member = (find(src_dict_name),
                                           find(trg_dict_name),
                                           find(data_name))
        if src_name is None or trg_name is None or data_member is None:
            return None     # unexpected layout -> caller falls back
        src_dict = to_dict(src_name, src_dict_size)
        trg_dict = to_dict(trg_name, trg_dict_size)
        for line in tf.extractfile(data_member):
            parts = line.decode('utf-8', 'replace').strip().split('\t')
            if len(parts) != 2:
                continue
            src = [src_dict.get(w, UNK)
                   for w in [START] + parts[0].split() + [END]]
            trg_raw = [trg_dict.get(w, UNK) for w in parts[1].split()]
            if len(src) > max_len or len(trg_raw) > max_len:
                continue
            trg_in = [trg_dict[START]] + trg_raw
            trg_out = trg_raw + [trg_dict[END]]
            pairs.append((src, trg_in, trg_out))
    return pairs, src_dict, trg_dict


class WMT14(_SyntheticTranslation):
    """WMT'14 en-fr. Real file: the reference's wmt14.tgz ({mode}/{mode}
    tab-separated corpus + src.dict/trg.dict members)."""

    def __init__(self, data_file=None, mode='train', dict_size=30000,
                 download=True):
        data_file = data_file or os.path.join(DATA_HOME, 'wmt14', 'wmt14.tgz')
        loaded = None
        if os.path.exists(data_file):
            loaded = _load_wmt_tar(data_file, mode, 'src.dict', 'trg.dict',
                                   '{}/{}'.format(mode, mode), dict_size)
        if loaded:
            self.pairs, self.src_dict, self.trg_dict = loaded
        else:
            super().__init__(mode, seed=6)
            return
        self.src_word_idx = self.src_dict
        self.trg_word_idx = self.trg_dict

    def __getitem__(self, idx):
        p = self.pairs[idx]
        if isinstance(p[0], list):
            return tuple(np.asarray(x, 'int64') for x in p)
        return super().__getitem__(idx)


class WMT16(_SyntheticTranslation):
    """WMT'16 en-de (BPE). Real file: the reference's wmt16.tar.gz
    (wmt16/{mode} corpus + wmt16/{lang}_{size}.dict vocab members)."""

    def __init__(self, data_file=None, mode='train', src_dict_size=30000,
                 trg_dict_size=30000, lang='en', download=True):
        data_file = data_file or os.path.join(DATA_HOME, 'wmt16',
                                              'wmt16.tar.gz')
        other = 'de' if lang == 'en' else 'en'
        loaded = None
        if os.path.exists(data_file):
            loaded = _load_wmt_tar(
                data_file, mode, f'{lang}.dict', f'{other}.dict',
                'wmt16/{}'.format(mode), src_dict_size, trg_dict_size)
        if loaded:
            self.pairs, self.src_dict, self.trg_dict = loaded
        else:
            super().__init__(mode, seed=7)
            return
        self.src_word_idx = self.src_dict
        self.trg_word_idx = self.trg_dict

    def __getitem__(self, idx):
        p = self.pairs[idx]
        if isinstance(p[0], list):
            return tuple(np.asarray(x, 'int64') for x in p)
        return super().__getitem__(idx)


class Conll05st(Dataset):
    """CoNLL-2005 SRL (test.wsj split, as in the reference).

    Real files: conll05st-tests.tar.gz with
    conll05st-release/test.wsj/{words,props}/test.wsj.{words,props}.gz plus
    the word/verb/target dict files (reference:
    python/paddle/text/datasets/conll05.py). The props column bracket tags
    ('(A0*', '*', '*)') expand to B-/I-/O sequences; one sample per
    (sentence, predicate) pair.
    """

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, mode='train',
                 download=True):
        data_file = data_file or os.path.join(
            DATA_HOME, 'conll05st', 'conll05st-tests.tar.gz')
        if os.path.exists(data_file):
            self._load_real(data_file, word_dict_file, verb_dict_file,
                            target_dict_file)
        else:
            rng = np.random.RandomState(8)
            self.samples = []
            for _ in range(256):
                ln = rng.randint(5, 30)
                words = rng.randint(0, 300, ln).astype('int64')
                pred = rng.randint(0, 50, ln).astype('int64')
                labels = rng.randint(0, 20, ln).astype('int64')
                self.samples.append((words, pred, labels))

    @staticmethod
    def _expand_props(col):
        """Bracket tags -> B-/I-/O label sequence for one predicate column."""
        out, cur, inside = [], 'O', False
        for tag in col:
            if tag == '*':
                out.append('I-' + cur if inside else 'O')
            elif tag == '*)':
                out.append('I-' + cur)
                inside = False
            elif '(' in tag:
                cur = tag[1:tag.find('*')]
                out.append('B-' + cur)
                inside = ')' not in tag
            else:
                out.append('O')
        return out

    def _load_real(self, data_file, word_dict_file, verb_dict_file,
                   target_dict_file):
        import gzip as _gz
        base = os.path.dirname(data_file)
        word_dict_file = word_dict_file or os.path.join(base, 'wordDict.txt')
        verb_dict_file = verb_dict_file or os.path.join(base, 'verbDict.txt')
        target_dict_file = target_dict_file or os.path.join(base,
                                                            'targetDict.txt')

        def load_dict(p):
            if not os.path.exists(p):
                return None
            with open(p) as f:
                return {line.strip(): i for i, line in enumerate(f)}

        self.word_dict = load_dict(word_dict_file) or {}
        self.verb_dict = load_dict(verb_dict_file) or {}
        self.label_dict = {}
        if os.path.exists(target_dict_file):
            tags = set()
            with open(target_dict_file) as f:
                for line in f:
                    line = line.strip()
                    if line.startswith(('B-', 'I-')):
                        tags.add(line[2:])
            for t in sorted(tags):
                self.label_dict['B-' + t] = len(self.label_dict)
                self.label_dict['I-' + t] = len(self.label_dict)
            self.label_dict['O'] = len(self.label_dict)

        self.samples = []
        pre = 'conll05st-release/test.wsj'
        with tarfile.open(data_file) as tf:
            wf = _gz.GzipFile(
                fileobj=tf.extractfile(f'{pre}/words/test.wsj.words.gz'))
            pf = _gz.GzipFile(
                fileobj=tf.extractfile(f'{pre}/props/test.wsj.props.gz'))
            sent, cols = [], []
            for wline, pline in zip(wf, pf):
                word = wline.decode().strip()
                props = pline.decode().strip().split()
                if not props:                       # sentence boundary
                    self._emit(sent, cols)
                    sent, cols = [], []
                else:
                    sent.append(word)
                    cols.append(props)
            self._emit(sent, cols)

    def _emit(self, sent, cols):
        if not cols:
            return
        n_pred = len(cols[0]) - 1
        verbs = [row[0] for row in cols if row[0] != '-']
        for j in range(n_pred):
            labels = self._expand_props([row[j + 1] for row in cols])
            if 'B-V' not in labels:
                continue
            # unknown -> in-vocabulary UNK (id 0), as in the reference loader;
            # unknown label tags -> 'O' (always last in label_dict)
            words = np.asarray(
                [self.word_dict.get(w.lower(), 0) for w in sent], 'int64')
            verb = verbs[j] if j < len(verbs) else '-'
            pred = np.full(len(sent), self.verb_dict.get(verb, 0), 'int64')
            o_id = self.label_dict.get('O', 0)
            lab = np.asarray([self.label_dict.get(t, o_id) for t in labels],
                             'int64')
            self.samples.append((words, pred, lab))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Viterbi decoding via lax.scan. Returns (scores, paths)."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    pot = potentials._value if isinstance(potentials, Tensor) else jnp.asarray(potentials)
    trans = transition_params._value if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    B, T, N = pot.shape

    lens = None
    if lengths is not None:
        lens = (lengths._value if isinstance(lengths, Tensor)
                else jnp.asarray(lengths)).astype(jnp.int32)

    def step(carry, inp):
        emit, t = inp
        score = carry                                   # [B, N]
        cand = score[:, :, None] + trans[None]          # [B, N, N]
        best = jnp.max(cand, axis=1) + emit
        idx = jnp.argmax(cand, axis=1)
        if lens is not None:
            # steps past a sequence's length pass the state through so the
            # final scores/backtrack reflect position length-1, not padding
            active = (t < lens)[:, None]
            best = jnp.where(active, best, score)
            idx = jnp.where(active, idx, jnp.arange(N)[None, :])
        return best, idx

    score0 = pot[:, 0]
    scores, idxs = jax.lax.scan(
        step, score0, (jnp.moveaxis(pot[:, 1:], 1, 0),
                       jnp.arange(1, T, dtype=jnp.int32)))
    final_best = jnp.argmax(scores, axis=-1)

    def backtrack(carry, idx_t):
        cur = carry
        prev = jnp.take_along_axis(idx_t, cur[:, None], axis=1)[:, 0]
        return prev, cur

    _, path_rev = jax.lax.scan(backtrack, final_best, idxs, reverse=True)
    paths = jnp.concatenate([jnp.moveaxis(path_rev, 0, 1),
                             final_best[:, None]], axis=1)
    return Tensor(jnp.max(scores, -1)), Tensor(paths.astype(jnp.int32))


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include)
