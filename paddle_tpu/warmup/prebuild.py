"""AOT prebuild: replay a warmup manifest ahead of traffic.

Each manifest entry is compiled with ``jit(...).lower(abstract).compile()``
over ``jax.ShapeDtypeStruct`` arguments — no real data, no device math.
Compiling through the *same* jit callables the live path uses means the
executable lands in their in-process tracing caches (a later real call with
matching avals neither retraces nor recompiles), and serving/Predictor
entries go further: the AOT ``Compiled`` object itself is seeded into the
bucket/shape caches, so live traffic reports literally zero compiles. When
the persistent cache (``persistent.py``) is enabled, every prebuilt
executable is also written to disk for the *next* process.

Entry dispatch:

- ``serving_bucket`` → ``engine=``: build + AOT-compile the bucket
  executable and ``put()`` it into the engine's ``BucketCompileCache``.
- ``train_step`` / ``accum_step`` → ``model=``: compile the hapi train step
  (or accum micro-step + apply) against abstract params/opt-state/PRNG-key
  avals — the training RNG stream is never consumed.
- ``eval_step`` → ``model=``: compile the eval/predict step.
- ``predictor`` → ``predictor=``: compile the padded-feed executable and
  seed ``Predictor._compiled``.
- ``gen_prefill`` / ``gen_decode`` → ``generation=``: compile the
  continuous-batching GenerationEngine's two executables (fixed-slot
  decode step + padded batch-1 prefill) after verifying the manifest's
  slot/page geometry matches the live engine.

Entries with no matching target are counted ``untargeted`` and skipped;
stale entries (shapes the current network can no longer trace) are warned
about and skipped — a manifest from last week must never crash today's
deploy. Telemetry: ``warmup.prebuild_ms`` histogram,
``warmup.prebuilt_total`` / ``warmup.prebuild_skipped`` counters.
"""
import os
import time
import warnings

import jax
import numpy as np

from .. import observability as _obs
from .manifest import Manifest, _sig_from_json, serving_bucket_entry


def _struct(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(int(d) for d in shape),
                                np.dtype(dtype))


def _tree_structs(tree):
    """Abstract (shape, dtype) skeleton of a pytree of arrays. Leaves
    committed to a multi-device mesh keep their NamedSharding (via
    parallel.mesh_engine.sharded_structs): an executable AOT-compiled for
    a mesh-sharded engine must expect exactly the placements the live
    path passes, or the first real call would recompile."""
    from ..parallel.mesh_engine import sharded_structs
    return sharded_structs(tree)


def _key_struct():
    """Aval of a PRNG key WITHOUT consuming the global RNG stream —
    prebuild must not perturb bit-exact training/resume behaviour."""
    return jax.eval_shape(lambda: jax.random.PRNGKey(0))


def _scalar_f32():
    return jax.ShapeDtypeStruct((), np.float32)


def _perf_analyze(label, compiled):
    """Publish the prebuilt executable's cost/memory analysis under the SAME
    label the live path uses, so perf.* series exist before first traffic
    and a later live ``note_step`` joins them into an MFU."""
    if _obs.enabled() and _obs.perf.analyzed(label) is None:
        _obs.perf.analyze_compiled(label, compiled)


# ---- per-kind prebuilders --------------------------------------------------

def _prebuild_bucket(engine, entry):
    bucket = int(entry['bucket'])
    sig = _sig_from_json(entry['inputs'])
    # The live path only ever queries at the engine's own precision; a
    # manifest captured at another precision still warms this engine's key.
    precision = engine._precision
    if engine._cache.peek(bucket, sig, precision) is not None:
        return False
    if bucket > engine.max_batch_size:
        raise ValueError(f'bucket {bucket} exceeds engine max_batch_size '
                         f'{engine.max_batch_size}')
    fn = engine._build(bucket, sig, precision)
    params = _tree_structs(engine._params)
    buffers = _tree_structs(engine._buffers)
    xs = [_struct((bucket,) + shape, dtype) for shape, dtype in sig]
    compiled = fn.lower(params, buffers, *xs).compile()
    _perf_analyze(f'serving.bucket{bucket}', compiled)
    return engine._cache.put(bucket, sig, precision, compiled)


def _opt_state_structs(model, param_structs):
    if getattr(model, '_opt_state', None) is not None:
        return _tree_structs(model._opt_state)
    if getattr(model, '_tstate', None) is not None:
        return _tree_structs(model._tstate.opt_state)
    return jax.eval_shape(model._optimizer.functional_init, param_structs)


def _prebuild_train(model, entry):
    if model._optimizer is None or model._loss is None:
        raise RuntimeError('prepare(optimizer, loss) must run before '
                           'train-step warmup')
    model._enter_mode(True)
    mode_key = (model._mode_sig(), model._amp_sig())
    fns = model._train_steps.get(mode_key)
    if fns is None:
        model._asp_sig = model._asp_signature()
        fns = model._build_train_step()
        model._train_steps[mode_key] = fns
    step, accum_step, apply_accum = fns
    params = _tree_structs(model._params_dict())
    buffers = _tree_structs(model._buffers_dict())
    inputs = tuple(_struct(s, d)
                   for s, d in _sig_from_json(entry.get('inputs') or []))
    labels = tuple(_struct(s, d)
                   for s, d in _sig_from_json(entry.get('labels') or []))
    key = _key_struct()
    opt_state = _opt_state_structs(model, params)
    if entry['kind'] == 'accum_step':
        _perf_analyze('hapi.accum_step',
                      accum_step.lower(params, buffers, params, key, inputs,
                                       labels).compile())
        _perf_analyze('hapi.apply_accum',
                      apply_accum.lower(params, opt_state, params,
                                        _scalar_f32(),
                                        _scalar_f32()).compile())
    else:
        _perf_analyze('hapi.train_step',
                      step.lower(params, buffers, opt_state, key,
                                 _scalar_f32(), inputs, labels).compile())
    return True


def _prebuild_eval(model, entry):
    model._enter_mode(False)
    in_sig = _sig_from_json(entry.get('inputs') or [])
    lab_sig = _sig_from_json(entry.get('labels') or [])
    cache_key = (model._mode_sig(), model._amp_sig(), in_sig, lab_sig)
    step = model._eval_steps.get(cache_key)
    if step is None:
        step = model._build_eval_step()
        model._eval_steps[cache_key] = step
    params = _tree_structs(model._params_dict())
    buffers = _tree_structs(model._buffers_dict())
    inputs = tuple(_struct(s, d) for s, d in in_sig)
    labels = tuple(_struct(s, d) for s, d in lab_sig)
    _perf_analyze('hapi.eval_step',
                  step.lower(params, buffers, _key_struct(), inputs,
                             labels).compile())
    return True


def _prebuild_generation(engine, entry):
    """AOT-compile one GenerationEngine executable (gen_prefill/gen_decode).
    The manifest's geometry must match the live engine — a mismatched
    entry is stale (caught by the strict/skip machinery), never silently
    compiled at the wrong shapes."""
    kind = entry['kind']
    geom = {'slots': engine.num_slots, 'page_size': engine.page_size,
            'num_pages': engine.num_pages,
            'prefill_width': engine.prefill_width,
            'table_width': engine.p_max}
    for k, v in geom.items():
        got = int(entry.get(k, v))
        if got != v:
            raise ValueError(
                f'generation entry {k}={got} does not match the live '
                f'engine ({k}={v})')
    if kind in engine._aot:
        return False
    pf, st = engine._fns_pair()
    params = _tree_structs(engine._params)
    pool = _tree_structs(engine._pool)
    if kind == 'gen_prefill':
        compiled = pf.lower(
            params, pool,
            _struct((1, engine.prefill_width), np.int32),
            _struct((1,), np.int32),    # start (prefix-cache tail offset)
            _struct((1,), np.int32),    # valid
            _struct((1, engine.p_max), np.int32),
            _struct((1,), np.uint32)).compile()
        _perf_analyze('gen.prefill', compiled)
    else:
        s = engine.num_slots
        compiled = st.lower(
            params, pool,
            _struct((s,), np.int32), _struct((s,), np.int32),
            _struct((s, engine.p_max), np.int32),
            _struct((s,), np.uint32)).compile()
        _perf_analyze('gen.decode', compiled)
    # hand the AOT executable to the engine's live path: jit's own call
    # cache would rebuild the executable on the first real invocation
    # even with the trace warm, costing one full XLA compile per fn
    engine._aot[kind] = compiled
    return True


def _prebuild_predictor(predictor, entry):
    key = _sig_from_json(entry['inputs'])
    fn = predictor._compiled.get(key)
    if fn is not None and not hasattr(fn, 'lower'):
        return False  # already an AOT executable
    fn = predictor._get_compiled(key)
    structs = [_struct(shape, dtype) for shape, dtype in key]
    compiled = fn.lower(*structs).compile()
    predictor._compiled[key] = compiled
    label = 'predictor.' + ';'.join(
        'x'.join(map(str, shape)) or 'scalar' for shape, _ in key)
    _perf_analyze(label, compiled)
    return True


# ---- driver ----------------------------------------------------------------

def prebuild(manifest, *, engine=None, model=None, predictor=None,
             generation=None, strict=False):
    """Replay ``manifest`` (a Manifest or a path to one) against the given
    targets. Returns a report dict: entries / prebuilt / already_cached /
    skipped / untargeted / total_ms (+ ``skips`` reasons).

    With ``strict=False`` (default) a stale entry — a signature the current
    network can no longer build — is warned about and skipped; with
    ``strict=True`` it raises."""
    if isinstance(manifest, (str, os.PathLike)):
        manifest = Manifest.load(manifest)
    handlers = {}
    if engine is not None:
        handlers['serving_bucket'] = lambda e: _prebuild_bucket(engine, e)
    if model is not None:
        handlers['train_step'] = lambda e: _prebuild_train(model, e)
        handlers['accum_step'] = lambda e: _prebuild_train(model, e)
        handlers['eval_step'] = lambda e: _prebuild_eval(model, e)
    if predictor is not None:
        handlers['predictor'] = lambda e: _prebuild_predictor(predictor, e)
    if generation is not None:
        handlers['gen_prefill'] = \
            lambda e: _prebuild_generation(generation, e)
        handlers['gen_decode'] = \
            lambda e: _prebuild_generation(generation, e)

    # Prebuild flips the network's train/eval mode to trace each step kind;
    # put it back so a live fit/eval after warmup starts where it left off.
    orig_mode = model._net_mode if model is not None else None

    report = {'entries': len(manifest), 'prebuilt': 0, 'already_cached': 0,
              'skipped': 0, 'untargeted': 0, 'skips': []}
    t_start = time.perf_counter()
    try:
        for entry in manifest:
            kind = entry.get('kind')
            handler = handlers.get(kind)
            if handler is None:
                report['untargeted'] += 1
                continue
            t0 = time.perf_counter()
            try:
                built = handler(entry)
            except Exception as e:
                if strict:
                    raise
                warnings.warn(
                    f'paddle_tpu.warmup: skipping stale manifest entry '
                    f'({kind}): {e!r}', RuntimeWarning, stacklevel=2)
                _obs.counter('warmup.prebuild_skipped',
                             {'kind': str(kind)}).inc()
                report['skipped'] += 1
                report['skips'].append(f'{kind}: {e}')
                continue
            if built:
                elapsed_ms = 1e3 * (time.perf_counter() - t0)
                _obs.histogram('warmup.prebuild_ms').observe(elapsed_ms)
                _obs.counter('warmup.prebuilt_total',
                             {'kind': str(kind)}).inc()
                report['prebuilt'] += 1
            else:
                report['already_cached'] += 1
    finally:
        if model is not None and orig_mode is not None:
            model._enter_mode(orig_mode)
    # mark the targets warm for the telemetry plane's /readyz probes
    for target in (engine, generation):
        if target is not None and hasattr(target, '_warmed'):
            target._warmed = True
    report['total_ms'] = round(1e3 * (time.perf_counter() - t_start), 3)
    return report


# ---- manifest synthesis ----------------------------------------------------

def _normalize_example_spec(spec):
    """Normalize a per-example input spec into ((shape, dtype), ...).

    Accepts: ``(shape, dtype)`` pairs (per-example, no batch dim),
    ``static.InputSpec`` objects or ``{'shape': .., 'dtype': ..}`` dicts
    (batched — the leading dim is stripped). Any remaining dynamic dim is
    an error: warmup needs concrete per-example shapes."""
    if spec is None:
        return None
    out = []
    for s in spec:
        if isinstance(s, dict):
            shape, dtype = tuple(s['shape'])[1:], s.get('dtype', 'float32')
        elif hasattr(s, 'shape') and hasattr(s, 'dtype') and \
                not isinstance(s, (tuple, list)):
            shape, dtype = tuple(s.shape)[1:], s.dtype
        else:
            shape, dtype = s
            shape = tuple(shape)
        if any(d is None or int(d) < 0 for d in shape):
            raise ValueError(
                f'input spec {s!r} has dynamic non-batch dims; warmup '
                'needs concrete per-example shapes')
        out.append((tuple(int(d) for d in shape), np.dtype(dtype).name))
    return tuple(out)


def all_buckets_manifest(engine, input_spec=None):
    """Synthesize a manifest covering the engine's whole bucket ladder for
    one input signature — warmup without a prior capture run. The spec
    comes from ``input_spec`` or from what the engine inferred from its
    backend (hapi ``Model._inputs`` / ``Predictor`` metadata)."""
    from ..serving.bucketing import bucket_sizes
    sig = _normalize_example_spec(
        input_spec if input_spec is not None
        else getattr(engine, '_example_spec', None))
    if sig is None:
        raise ValueError(
            "warmup='all_buckets' needs an input signature: pass "
            "input_spec= (e.g. [((8,), 'float32')] per example) or build "
            'the engine from a hapi Model / Predictor with input specs')
    manifest = Manifest()
    for bucket in bucket_sizes(engine.max_batch_size):
        manifest.add(serving_bucket_entry(bucket, sig, engine._precision,
                                          max_batch=engine.max_batch_size))
    return manifest
