"""Warmup manifests: the recorded compile-signature set of one run.

The TPP discipline (arxiv 2104.05755) keeps a production process on a small
closed set of shape-stable executables — which makes that set a finite,
enumerable artifact. ``capture()`` records every distinct signature the
process compiles (serving bucket keys, hapi train/eval step signatures,
Predictor shape keys) into a JSON manifest; ``warmup.prebuild(manifest)``
replays it ahead of traffic in the next process so the first request runs an
already-built program.

Entries are plain JSON dicts keyed by ``kind``:

- ``serving_bucket``: per-example input signature + padded bucket size
- ``train_step`` / ``accum_step``: full input/label shapes of a hapi step
- ``eval_step``: full input/label shapes of a hapi eval/predict step
- ``predictor``: the padded feed key of an inference.Predictor.run

Capture is process-global (one active manifest at a time) and thread-safe;
hooks in the serving engine / hapi model / Predictor call ``record()`` only
while a capture is active, so the disabled-mode cost on hot paths is one
``sys.modules`` lookup at the call site.
"""
import contextlib
import json
import os
import threading

MANIFEST_VERSION = 1

ENTRY_KINDS = ('serving_bucket', 'train_step', 'accum_step', 'eval_step',
               'predictor', 'gen_prefill', 'gen_decode')


def _sig_to_json(sig):
    return [[list(int(d) for d in shape), str(dtype)]
            for shape, dtype in sig]


def _sig_from_json(doc):
    return tuple((tuple(int(d) for d in shape), str(dtype))
                 for shape, dtype in doc)


def array_sig(arrays):
    """(full shape, dtype) signature of a concrete argument list — the same
    tuples the hapi eval-step cache keys on."""
    return tuple((tuple(int(d) for d in getattr(a, 'shape', ())),
                  str(getattr(a, 'dtype', ''))) for a in arrays)


def serving_bucket_entry(bucket, sig, precision, max_batch=None):
    """One serving executable: ``sig`` is the per-example input signature
    (``serving.input_signature``), ``bucket`` the padded batch size."""
    entry = {'kind': 'serving_bucket', 'bucket': int(bucket),
             'inputs': _sig_to_json(sig), 'precision': str(precision)}
    if max_batch is not None:
        entry['max_batch'] = int(max_batch)
    return entry


def train_step_entry(inputs_sig, labels_sig, accumulate=False):
    """One hapi train-step signature (full batch shapes). ``accumulate``
    marks the gradient-merge path (accum micro-step + apply)."""
    return {'kind': 'accum_step' if accumulate else 'train_step',
            'inputs': _sig_to_json(inputs_sig),
            'labels': _sig_to_json(labels_sig)}


def eval_step_entry(inputs_sig, labels_sig):
    return {'kind': 'eval_step', 'inputs': _sig_to_json(inputs_sig),
            'labels': _sig_to_json(labels_sig)}


def predictor_entry(shapes_key, precision='float32'):
    """One Predictor executable: ``shapes_key`` is the padded feed key
    Predictor.run compiles for (full shapes incl. batch dim)."""
    return {'kind': 'predictor', 'inputs': _sig_to_json(shapes_key),
            'precision': str(precision)}


def generation_entry(kind, *, slots, page_size, num_pages, prefill_width,
                     table_width):
    """One GenerationEngine executable (``gen_prefill`` or ``gen_decode``):
    the geometry fields pin the batch-independent shapes of the continuous-
    batching prefill/step programs, so prebuild can verify the replaying
    engine was built with the same slot/page layout."""
    if kind not in ('gen_prefill', 'gen_decode'):
        raise ValueError(f'kind must be gen_prefill or gen_decode, '
                         f'got {kind!r}')
    return {'kind': kind, 'slots': int(slots), 'page_size': int(page_size),
            'num_pages': int(num_pages),
            'prefill_width': int(prefill_width),
            'table_width': int(table_width)}


class Manifest:
    """Deduplicated, insertion-ordered set of warmup entries with atomic
    JSON persistence. Safe to ``add`` from several threads (the serving
    dispatch thread records while user threads train)."""

    def __init__(self, entries=None, meta=None):
        self._lock = threading.Lock()
        self.meta = dict(meta or {})
        self.entries = []
        self._keys = set()
        for e in entries or ():
            self.add(e)

    def add(self, entry):
        """Add one entry; returns False (and keeps the first copy) when an
        identical entry was already recorded."""
        key = json.dumps(entry, sort_keys=True)
        with self._lock:
            if key in self._keys:
                return False
            self._keys.add(key)
            self.entries.append(dict(entry))
            return True

    def __len__(self):
        with self._lock:
            return len(self.entries)

    def __iter__(self):
        with self._lock:
            return iter(list(self.entries))

    def counts(self):
        """Per-kind entry counts (manifest forensics, warmup reports)."""
        out = {}
        for e in self:
            k = e.get('kind', '?')
            out[k] = out.get(k, 0) + 1
        return out

    def to_json(self):
        import jax
        from ..version import full_version
        meta = dict(self.meta)
        meta.setdefault('framework', full_version)
        meta.setdefault('jax', jax.__version__)
        with self._lock:
            entries = list(self.entries)
        return {'version': MANIFEST_VERSION, 'meta': meta,
                'entries': entries}

    def save(self, path):
        """Atomic write (tmp -> fsync -> replace): a crash mid-save never
        leaves a truncated manifest for the next process to choke on."""
        doc = self.to_json()
        tmp = f'{path}.tmp.{os.getpid()}'
        with open(tmp, 'w') as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path):
        with open(path) as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or not isinstance(doc.get('entries'),
                                                       list):
            raise ValueError(f'{path!r} is not a warmup manifest')
        return cls(entries=doc['entries'], meta=doc.get('meta'))


# ---- process-global capture state -----------------------------------------

_capture_lock = threading.Lock()
_active = None


def capturing():
    """True while a capture is active (the hooks' fast guard)."""
    return _active is not None


def capture_start(manifest=None):
    """Begin recording compile signatures into ``manifest`` (a fresh one by
    default). Re-entrant: a second start joins the active capture."""
    global _active
    with _capture_lock:
        if _active is None:
            _active = manifest if manifest is not None else Manifest()
        return _active


def capture_stop():
    """Stop recording; returns the captured manifest (None if inactive)."""
    global _active
    with _capture_lock:
        manifest, _active = _active, None
        return manifest


@contextlib.contextmanager
def capture(manifest=None):
    """``with warmup.capture() as man:`` — record every signature compiled
    in the block, then ``man.save(path)`` it for the next process."""
    manifest = capture_start(manifest)
    try:
        yield manifest
    finally:
        capture_stop()


def record(entry):
    """Record one entry into the active capture; no-op when inactive."""
    manifest = _active
    if manifest is not None:
        manifest.add(entry)
