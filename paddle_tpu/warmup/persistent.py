"""Persistent XLA compile cache behind one switch.

``enable_persistent_cache(dir)`` points JAX's on-disk compilation cache at
``dir/<cache key>`` where the key folds in the framework version, the JAX
version, and the backend — a cache written by one build/backend is never
read by another. Activation is corruption tolerant: the directory probe
runs under ``fault.retry`` with a ``warmup.cache`` inject point, and any
persistent failure degrades to cold in-process compiles with a warning
instead of taking the run down. Individual corrupt cache *entries* are
handled by JAX itself (``jax_raise_persistent_cache_errors=False`` → the
entry is recompiled, never raised).

Cache traffic is observable: JAX's monitoring events are forwarded into
the PR-4 registry as ``warmup.cache.hit_total`` / ``warmup.cache.miss_total``
counters, and ``cache_stats()`` reports entry count / on-disk bytes (also
exported as ``warmup.cache.bytes`` / ``warmup.cache.entries`` gauges).

Zero-code activation: set ``PADDLE_TPU_COMPILE_CACHE=<dir>`` — the serving
engine and hapi Model call ``ensure_persistent_cache()`` on construction.
"""
import os
import threading
import warnings

import jax

from .. import fault
from .. import observability as _obs

ENV_CACHE_DIR = 'PADDLE_TPU_COMPILE_CACHE'

_HIT_EVENT = '/jax/compilation_cache/cache_hits'
_MISS_EVENT = '/jax/compilation_cache/cache_misses'

_lock = threading.Lock()
_cache_dir = None
_listener_installed = False
_env_attempted = False


def cache_key_component(backend=None):
    """Directory component that keys the cache: framework version + JAX
    version + backend. Executables are not portable across any of these."""
    from ..version import full_version
    if backend is None:
        backend = jax.default_backend()
    return f'pt{full_version}-jax{jax.__version__}-{backend}'


def _on_monitoring_event(name, **kwargs):
    if name == _HIT_EVENT:
        _obs.counter('warmup.cache.hit_total').inc()
    elif name == _MISS_EVENT:
        _obs.counter('warmup.cache.miss_total').inc()


def _reset_jax_cache():
    """Drop JAX's in-memory cache singleton so the next compile
    re-initializes it from the just-updated config — the singleton is
    pinned at first compile, so enabling mid-process (or re-pointing the
    dir) is silently ignored without this."""
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:
        pass


def _install_listener():
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        try:
            jax.monitoring.register_event_listener(_on_monitoring_event)
            _listener_installed = True
        except Exception:
            # monitoring API unavailable: counters stay 0, cache still works
            pass


def enable_persistent_cache(directory=None, *, backend=None,
                            min_compile_time_secs=0.0):
    """Enable the on-disk compile cache under ``directory`` (or
    ``$PADDLE_TPU_COMPILE_CACHE``). Returns the resolved per-version cache
    path, or None when the directory is unusable — the process then falls
    back to cold compiles and keeps running."""
    global _cache_dir
    directory = directory or os.environ.get(ENV_CACHE_DIR)
    if not directory:
        raise ValueError('enable_persistent_cache needs a directory '
                         f'(argument or ${ENV_CACHE_DIR})')
    resolved = os.path.join(os.path.expanduser(str(directory)),
                            cache_key_component(backend))

    def _activate():
        fault.inject('warmup.cache')
        os.makedirs(resolved, exist_ok=True)
        # Write probe: catch read-only mounts / quota exhaustion / a file
        # squatting on the path now, not at the first compile.
        probe = os.path.join(resolved, f'.probe.{os.getpid()}')
        with open(probe, 'w') as f:
            f.write('ok')
        os.remove(probe)
        jax.config.update('jax_compilation_cache_dir', resolved)
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          float(min_compile_time_secs))
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
        # A corrupt/unreadable entry must mean "recompile", never "crash".
        jax.config.update('jax_raise_persistent_cache_errors', False)
        _reset_jax_cache()

    try:
        fault.retry(_activate, retries=3, backoff=0.05,
                    exceptions=(OSError, fault.InjectedFault))
    except Exception as e:
        warnings.warn(
            f'paddle_tpu.warmup: persistent compile cache unavailable at '
            f'{resolved!r} ({e!r}); continuing with cold compiles',
            RuntimeWarning, stacklevel=2)
        _obs.counter('warmup.cache.fallback_total').inc()
        with _lock:
            _cache_dir = None
        return None
    _install_listener()
    with _lock:
        _cache_dir = resolved
    return resolved


def disable_persistent_cache():
    """Detach the on-disk cache (compiles stay in-process only)."""
    global _cache_dir
    with _lock:
        _cache_dir = None
    try:
        jax.config.update('jax_compilation_cache_dir', None)
        _reset_jax_cache()
    except Exception:
        pass


def persistent_cache_dir():
    """The active resolved cache path, or None."""
    return _cache_dir


def ensure_persistent_cache():
    """Idempotent env-knob activation: enable from
    ``$PADDLE_TPU_COMPILE_CACHE`` once per process. A failed attempt is
    remembered so construction paths don't retry the probe forever."""
    global _env_attempted
    if _cache_dir is not None or _env_attempted:
        return _cache_dir
    _env_attempted = True
    directory = os.environ.get(ENV_CACHE_DIR)
    if not directory:
        return None
    return enable_persistent_cache(directory)


def cache_stats():
    """Hit/miss counters plus on-disk entry count and bytes of the active
    cache dir. Also refreshes the ``warmup.cache.bytes``/``entries``
    gauges."""
    directory = _cache_dir
    stats = {'dir': directory, 'entries': 0, 'bytes': 0,
             'hit_total': _obs.counter('warmup.cache.hit_total').value,
             'miss_total': _obs.counter('warmup.cache.miss_total').value}
    if directory and os.path.isdir(directory):
        for root, _dirs, files in os.walk(directory):
            for name in files:
                try:
                    stats['bytes'] += os.path.getsize(
                        os.path.join(root, name))
                    stats['entries'] += 1
                except OSError:
                    continue
    _obs.gauge('warmup.cache.bytes').set(stats['bytes'])
    _obs.gauge('warmup.cache.entries').set(stats['entries'])
    return stats
