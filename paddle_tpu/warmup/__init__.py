"""paddle_tpu.warmup — persistent compile cache + AOT warmup manifests.

Kills cold start on both ends of the lifecycle:

- **Persistent compile cache** (``persistent.py``): one switch points
  JAX's on-disk compilation cache at a framework-version+backend-keyed
  directory, with corruption-tolerant fallback and ``warmup.cache.*``
  hit/miss/bytes telemetry.
- **Warmup manifests** (``manifest.py``): ``capture()`` records every
  distinct compiled signature of a run — serving bucket keys, hapi
  train/eval step signatures, Predictor feed keys — into a JSON manifest.
- **AOT prebuild** (``prebuild.py``): ``prebuild(manifest, ...)`` replays
  the manifest with abstract ``ShapeDtypeStruct`` args ahead of traffic,
  populating the in-process caches and the persistent cache.

Recipe::

    from paddle_tpu import warmup, serving

    warmup.enable_persistent_cache('/var/cache/paddle_tpu')

    # capture run (once, e.g. in staging)
    with warmup.capture() as man:
        engine = serving.InferenceEngine(net, max_batch_size=64)
        ... live or synthetic traffic ...
    man.save('warmup.json')

    # every later process: first request runs an already-built program
    engine = serving.InferenceEngine(net, max_batch_size=64,
                                     warmup='warmup.json')

Env knob: ``PADDLE_TPU_COMPILE_CACHE=<dir>`` enables the persistent cache
without code changes (picked up by the serving engine and hapi Model).
"""
from .manifest import (Manifest, array_sig, capture, capture_start,  # noqa: F401
                       capture_stop, capturing, eval_step_entry,
                       generation_entry, predictor_entry, record,
                       serving_bucket_entry, train_step_entry)
from .persistent import (ENV_CACHE_DIR, cache_key_component,  # noqa: F401
                         cache_stats, disable_persistent_cache,
                         enable_persistent_cache, ensure_persistent_cache,
                         persistent_cache_dir)
from .prebuild import all_buckets_manifest, prebuild  # noqa: F401

__all__ = [
    'Manifest', 'capture', 'capture_start', 'capture_stop', 'capturing',
    'record', 'array_sig', 'serving_bucket_entry', 'train_step_entry',
    'eval_step_entry', 'predictor_entry', 'generation_entry',
    'enable_persistent_cache', 'disable_persistent_cache',
    'ensure_persistent_cache', 'persistent_cache_dir', 'cache_stats',
    'cache_key_component', 'ENV_CACHE_DIR',
    'prebuild', 'all_buckets_manifest',
]
