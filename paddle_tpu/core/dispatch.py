"""Op dispatch: run a pure jnp/lax function eagerly, recording a vjp tape node
when any input requires grad.

Design: every public op body is a *pure* function over jax arrays. Eagerly we
unwrap Tensors, call (optionally through jax.vjp for autograd), and wrap
results. Under jax.jit tracing the same pure functions run on tracers, so the
whole op library doubles as the static-graph lowering (reference's analogue:
fluid op kernels + grad-op registry, paddle/fluid/framework/op_registry.h).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .tensor import DeviceResidentRef, Tensor, TapeNode, _grad_enabled
from . import dtype as dtypes


def _unwrap(x):
    if isinstance(x, Tensor):
        v = x._value
        # a device-resident param touched by eager user code: resolve the
        # live array out of the executor's train state
        return v.materialize() if type(v) is DeviceResidentRef else v
    return x


def _is_diff_tensor(x):
    return (isinstance(x, Tensor) and not x.stop_gradient
            and jnp.issubdtype(x.dtype, jnp.inexact))


def _map_structure(fn, obj):
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_structure(fn, o) for o in obj)
    if isinstance(obj, dict):
        return {k: _map_structure(fn, v) for k, v in obj.items()}
    return fn(obj)


def apply_op(pure_fn, *args, **kwargs):
    """Execute pure_fn on unwrapped args; record tape if needed.

    Tensor leaves may appear at top level of args or KWARGS, or one level
    inside list/tuple values (e.g. concat([t1, t2]),
    layer_norm(x, shape, weight=w)).
    """
    diff = []           # list of (path, Tensor); path[0] is an arg index
                        # or ('kw', name) addressing a keyword argument

    def scan(obj, path):
        if _is_diff_tensor(obj):
            diff.append((path, obj))
        elif isinstance(obj, (list, tuple)):
            for i, o in enumerate(obj):
                scan(o, path + (i,))

    if _grad_enabled():
        for i, a in enumerate(args):
            scan(a, (i,))
        for k, v in kwargs.items():
            scan(v, (('kw', k),))

    def _unwrapped_kwargs():
        return {k: _map_structure(_unwrap, v) for k, v in kwargs.items()}

    if not diff:
        out = pure_fn(*_map_structure(_unwrap, list(args)),
                      **_unwrapped_kwargs())
        res = _wrap_outputs(out, node=None)
        _maybe_record_replay(pure_fn, args, kwargs, res)
        return res

    paths = [p for p, _ in diff]
    diff_tensors = [t for _, t in diff]

    def substitute(vals):
        new_args = list(_map_structure(_unwrap, list(args)))
        new_kwargs = _unwrapped_kwargs()
        for path, v in zip(paths, vals):
            store = new_kwargs if isinstance(path[0], tuple) else new_args
            key = path[0][1] if isinstance(path[0], tuple) else path[0]
            if len(path) == 1:
                store[key] = v
            else:
                seq = list(store[key])
                seq[path[1]] = v
                store[key] = seq
        return new_args, new_kwargs

    def pure_on_diff(vals):
        new_args, new_kwargs = substitute(vals)
        return pure_fn(*new_args, **new_kwargs)

    primals = [_unwrap(t) for t in diff_tensors]
    out, vjp_fn = jax.vjp(pure_on_diff, primals)

    flat_out, is_seq = (list(out), True) if isinstance(out, (list, tuple)) else ([out], False)
    out_tensors = [Tensor(o, stop_gradient=False) for o in flat_out]
    if is_seq:
        container = type(out)
        node_vjp = lambda cots: vjp_fn(container(cots))[0]
    else:
        node_vjp = lambda cots: vjp_fn(cots[0])[0]
    node = TapeNode(node_vjp, diff_tensors, out_tensors,
                    replay_fn=pure_on_diff, out_is_seq=is_seq,
                    out_container=container if is_seq else None)
    for i, t in enumerate(out_tensors):
        t._node = node
        t._out_idx = i
    if is_seq:
        res = type(out)(out_tensors) if isinstance(out, tuple) else out_tensors
    else:
        res = out_tensors[0]
    _maybe_record_replay(pure_fn, args, kwargs, res)
    return res


def _maybe_record_replay(pure_fn, args, kwargs, res):
    """In static-graph mode, stamp outputs with enough info to recompute them
    from fed placeholders — this is the Program that static.Executor replays
    (and jit-compiles). Reference analogue: ops appended to ProgramDesc."""
    from ..utils import misc
    if not misc.in_static_mode():
        return
    outs = res if isinstance(res, (list, tuple)) else [res]
    for i, t in enumerate(outs):
        if isinstance(t, Tensor):
            t._replay = (pure_fn, args, kwargs, i, isinstance(res, (list, tuple)))


def _wrap_outputs(out, node):
    if isinstance(out, (list, tuple)):
        return type(out)(Tensor(o) if not isinstance(o, Tensor) else o for o in out)
    return Tensor(out)


# amp/__init__.py installs a hook here that bf16-casts white-listed op inputs.
amp_cast_hook = None


def op(pure_fn):
    """Decorator: expose a pure jnp function as an eager+autograd op."""
    name = pure_fn.__name__

    @functools.wraps(pure_fn)
    def wrapper(*args, **kwargs):
        kwargs.pop('name', None)
        if amp_cast_hook is not None:
            args = amp_cast_hook(name, list(args))
        return apply_op(pure_fn, *args, **kwargs)
    wrapper.pure = pure_fn
    return wrapper


def elementwise_op(name, fn, *tensors, **kwargs):
    """Helper to apply an inline lambda as an op."""
    return apply_op(fn, *tensors, **kwargs)
