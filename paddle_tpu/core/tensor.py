"""Eager Tensor with tape-based autograd over jax.vjp.

This is the TPU-native analogue of Paddle's dygraph VarBase
(reference: paddle/fluid/imperative/layer.h, python/paddle/fluid/dygraph/varbase_patch_methods.py).
Instead of a C++ grad-op graph, every differentiable op call records a
``jax.vjp`` closure; ``Tensor.backward()`` replays them in reverse creation
order. Ops themselves are pure jnp/lax functions, so the same op library is
reused verbatim under ``jax.jit`` tracing for the static/compiled path.
"""
from __future__ import annotations

import contextlib
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes

_state = threading.local()

# Monotone counter bumped on every external in-place Tensor value swap
# (`_replace_value`): the hapi async executor snapshots it to detect that a
# parameter/buffer was mutated behind its device-resident TrainState and must
# be re-captured before the next compiled step.
_MUTATION_VERSION = 0


def mutation_version():
    return _MUTATION_VERSION


def _bump_mutation_version():
    global _MUTATION_VERSION
    _MUTATION_VERSION += 1


class DeviceResidentRef:
    """Placeholder standing in for ``Tensor._value`` while the real array
    lives in a Model's device-resident train state (hapi async executor).

    The executor donates the previous step's param/opt buffers to XLA, so a
    Tensor must not keep a direct reference to an array that the next step
    will invalidate. Instead it holds this ref, which resolves the CURRENT
    array out of the owning store on first touch (``materialize``), writes it
    back into the owning Tensor, and flags the store so the executor knows to
    re-install refs before the next donated step. shape/dtype are served
    statically (donation never changes them) so summary/repr-style metadata
    reads don't force a device sync.
    """

    __slots__ = ('_store_obj', '_store_attr', '_key', '_owner', '_shape',
                 '_dtype')

    def __init__(self, store_obj, store_attr, key, owner, shape, dtype):
        self._store_obj = store_obj
        self._store_attr = store_attr
        self._key = key
        self._owner = weakref.ref(owner)
        self._shape = tuple(shape)
        self._dtype = dtype

    def materialize(self):
        val = getattr(self._store_obj, self._store_attr)[self._key]
        self._store_obj.refs_dirty = True
        owner = self._owner()
        if owner is not None and owner._value is self:
            owner._value = val
        return val

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        out = 1
        for s in self._shape:
            out *= int(s)
        return out

    def __jax_array__(self):
        return self.materialize()

    def __array__(self, dtype=None):
        a = np.asarray(self.materialize())
        return a.astype(dtype) if dtype is not None else a

    def __getattr__(self, name):
        return getattr(self.materialize(), name)

    def __getitem__(self, idx):
        return self.materialize()[idx]

    def __len__(self):
        return self._shape[0]

    def __float__(self):
        return float(np.asarray(self.materialize()))

    def __int__(self):
        return int(np.asarray(self.materialize()))

    def __bool__(self):
        return builtins_bool(self.materialize())

    def __repr__(self):
        return (f'DeviceResidentRef({self._store_attr}[{self._key!r}], '
                f'shape={list(self._shape)}, dtype={self._dtype})')


def _grad_enabled():
    return getattr(_state, 'grad_enabled', True)


@contextlib.contextmanager
def no_grad_ctx():
    prev = _grad_enabled()
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad_ctx():
    prev = _grad_enabled()
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


class TapeNode:
    """One recorded differentiable op: vjp closure + input/output bookkeeping.

    ``replay_fn`` (when present) is the node's pure primal function over the
    list of diff-input VALUES — double-backward (paddle.grad with
    create_graph=True) re-derives jax.vjp from it so the backward pass can
    itself be taped; ``out_is_seq``/``out_container`` describe the primal
    output structure for rebuilding cotangents."""

    __slots__ = ('vjp_fn', 'inputs', 'out_specs', 'out_refs', 'index',
                 'replay_fn', 'out_is_seq', 'out_container', '__weakref__')
    _counter = 0

    def __init__(self, vjp_fn, inputs, outputs, replay_fn=None,
                 out_is_seq=False, out_container=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs              # list[Tensor] (the diff inputs)
        self.out_specs = [(o.shape, o.dtype) for o in outputs]
        self.out_refs = [weakref.ref(o) for o in outputs]
        self.replay_fn = replay_fn
        self.out_is_seq = out_is_seq
        self.out_container = out_container
        TapeNode._counter += 1
        self.index = TapeNode._counter


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


class Tensor:
    """Eager tensor. ``stop_gradient`` defaults to True (Paddle semantics);
    Parameters set it False. Holds a ``jax.Array`` (or a tracer inside jit)."""

    __array_priority__ = 100

    def __init__(self, value, stop_gradient=True, name=None):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self.name = name
        self.grad = None
        self._node = None       # creator TapeNode
        self._out_idx = 0       # which output of the creator
        self._retain = False
        self.is_leaf_hint = True

    # -- basic properties ------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def T(self):
        from ..tensor.linalg import transpose_last2
        return transpose_last2(self)

    @property
    def place(self):
        try:
            dev = list(self._value.devices())[0]
            return str(dev)
        except Exception:
            return 'TracedPlace'

    @property
    def is_leaf(self):
        return self._node is None

    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __len__(self):
        return self._value.shape[0]

    def __repr__(self):
        try:
            val = np.asarray(self._value)
            body = np.array2string(val, precision=4, separator=', ')
        except Exception:
            body = f'<traced {self._value}>'
        return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    def __jax_array__(self):
        return self._value

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def __bool__(self):
        return builtins_bool(self._value)

    def __int__(self):
        return int(np.asarray(self._value))

    def __float__(self):
        return float(np.asarray(self._value))

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(self._value.shape[0]):
            yield self[i]

    # -- grad machinery --------------------------------------------------
    def retain_grads(self):
        self._retain = True

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def clone(self):
        from ..core.dispatch import elementwise_op
        return elementwise_op('clone', lambda x: x + 0, self)

    def _replace_value(self, new_value):
        """In-place value swap (optimizer updates, set_value)."""
        if isinstance(new_value, Tensor):
            new_value = new_value._value
        self._value = new_value if isinstance(new_value, (jax.Array, jax.core.Tracer)) \
            else jnp.asarray(new_value)
        self._node = None
        _bump_mutation_version()

    def set_value(self, value):
        self._replace_value(value)

    def backward(self, grad_tensor=None, retain_graph=False):
        run_backward(self, grad_tensor, retain_graph)

    # -- python operators: filled in by paddle_tpu.tensor modules --------


def builtins_bool(x):
    import builtins
    return builtins.bool(np.asarray(x))


def _node_backward_taped(node, cot_tensors):
    """Differentiable backward of one node: re-derive jax.vjp from the
    node's replayed primal function and run it THROUGH the dispatch layer,
    so the produced gradients carry their own tape nodes (double-backward,
    reference: the grad-of-grad op graph dy2static/backward builds)."""
    from .dispatch import apply_op

    def bwd_pure(primal_vals, cot_vals):
        import jax as _jax
        _, vjp = _jax.vjp(node.replay_fn, list(primal_vals))
        cot = (node.out_container(cot_vals) if node.out_is_seq
               else cot_vals[0])
        (gs,) = vjp(cot)
        return tuple(gs)

    out = apply_op(bwd_pure, list(node.inputs), list(cot_tensors))
    return out if isinstance(out, tuple) else (out,)


def run_backward(root: Tensor, grad_tensor=None, retain_graph=False,
                 create_graph=False):
    if create_graph:
        return _run_backward_create_graph(root, grad_tensor)
    if root._node is None:
        # leaf: grad of itself
        if not root.stop_gradient:
            g = jnp.ones_like(root._value) if grad_tensor is None else (
                grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor))
            root.grad = Tensor(g) if root.grad is None else Tensor(root.grad._value + g)
        return

    if grad_tensor is None:
        seed = jnp.ones_like(root._value)
    else:
        seed = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # Collect reachable nodes via DFS, then process in reverse creation order
    # (creation order is a topological order for an eager tape).
    nodes = {}
    stack = [root._node]
    while stack:
        n = stack.pop()
        if n.index in nodes:
            continue
        nodes[n.index] = n
        for t in n.inputs:
            if t._node is not None:
                stack.append(t._node)

    # pending cotangents keyed by id(tensor)
    grads = {id(root): seed}
    tensor_of = {id(root): root}

    for idx in sorted(nodes.keys(), reverse=True):
        node = nodes[idx]
        if node.vjp_fn is None:
            raise RuntimeError(
                'Trying to backward through the graph a second time; '
                'call backward(retain_graph=True) the first time.')
        cots = []
        any_grad = False
        for i, (shape, dt) in enumerate(node.out_specs):
            ref = node.out_refs[i]()
            g = grads.pop(id(ref), None) if ref is not None else None
            if g is None:
                cots.append(jnp.zeros(shape, dt))
            else:
                any_grad = True
                cots.append(g)
        if not any_grad:
            continue
        in_grads = node.vjp_fn(tuple(cots))
        if not retain_graph:
            node.vjp_fn = None
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if t._node is None or t._retain:
                # leaf (or retained): accumulate into .grad
                if not t.stop_gradient:
                    t.grad = Tensor(g) if t.grad is None else Tensor(t.grad._value + g)
            if t._node is not None:
                k = id(t)
                tensor_of[k] = t
                grads[k] = g if k not in grads else grads[k] + g


def collect_leaf_tensors(root: Tensor):
    """All leaf tensors reachable from ``root``'s tape (the tensors whose
    ``.grad`` a backward pass would touch)."""
    leaves = []
    if root._node is None:
        return [root]
    seen = set()
    stack = [root._node]
    seen_t = set()
    while stack:
        n = stack.pop()
        if n.index in seen:
            continue
        seen.add(n.index)
        for t in n.inputs:
            if t._node is not None:
                stack.append(t._node)
            elif id(t) not in seen_t:
                seen_t.add(id(t))
                leaves.append(t)
    return leaves


def _run_backward_create_graph(root: Tensor, grad_tensor=None):
    """Backward pass whose cotangent arithmetic is itself taped: all
    bookkeeping holds TENSORS and every node's vjp re-runs through the
    dispatch layer (_node_backward_taped), so resulting .grad tensors are
    differentiable (paddle.grad(..., create_graph=True) semantics). The
    graph is implicitly retained (node.vjp_fn is never dropped)."""
    seed = (Tensor(jnp.ones_like(root._value)) if grad_tensor is None else
            (grad_tensor if isinstance(grad_tensor, Tensor)
             else Tensor(jnp.asarray(grad_tensor))))
    if root._node is None:
        if not root.stop_gradient:
            root.grad = seed if root.grad is None else root.grad + seed
        return

    nodes = {}
    stack = [root._node]
    while stack:
        n = stack.pop()
        if n.index in nodes:
            continue
        nodes[n.index] = n
        for t in n.inputs:
            if t._node is not None:
                stack.append(t._node)

    grads = {id(root): seed}           # id(tensor) -> cotangent TENSOR

    for idx in sorted(nodes.keys(), reverse=True):
        node = nodes[idx]
        if node.replay_fn is None:
            raise RuntimeError(
                'create_graph=True needs the node replay payload; this '
                'graph was built without it (PyLayer/custom op?)')
        cots = []
        any_grad = False
        for i, (shape, dt) in enumerate(node.out_specs):
            ref = node.out_refs[i]()
            g = grads.pop(id(ref), None) if ref is not None else None
            if g is None:
                cots.append(Tensor(jnp.zeros(shape, dt)))
            else:
                any_grad = True
                cots.append(g)
        if not any_grad:
            continue
        in_grads = _node_backward_taped(node, cots)
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if t._node is None or t._retain:
                if not t.stop_gradient:
                    t.grad = g if t.grad is None else t.grad + g
            if t._node is not None:
                k = id(t)
                grads[k] = g if k not in grads else grads[k] + g


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor — reference: python/paddle/tensor/creation.py:to_tensor."""
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(dtypes.convert_dtype(dtype))
        return Tensor(v, stop_gradient=stop_gradient)
    if isinstance(data, (jax.Array, jax.core.Tracer)):
        v = data
    else:
        arr = np.asarray(data)
        if dtype is None and arr.dtype == np.float64:
            arr = arr.astype(np.float32)   # paddle default float32
        v = jnp.asarray(arr)
    if dtype is not None:
        v = v.astype(dtypes.convert_dtype(dtype))
    return Tensor(v, stop_gradient=stop_gradient)
