"""Dtype aliases with Paddle's names, backed by JAX dtypes.

Reference: python/paddle/framework/dtype.py (paddle.float32 etc.).
"""
import jax.numpy as jnp
import numpy as np

bool = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    'bool': bool, 'uint8': uint8, 'int8': int8, 'int16': int16,
    'int32': int32, 'int64': int64, 'float16': float16,
    'bfloat16': bfloat16, 'float32': float32, 'float64': float64,
    'complex64': complex64, 'complex128': complex128,
}


def convert_dtype(dtype):
    """Normalize a string / numpy / jax dtype spec to a numpy dtype-like."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return _STR2DTYPE[dtype]
    return np.dtype(dtype).type if not hasattr(dtype, 'dtype') else dtype


def dtype_name(dtype):
    return np.dtype(dtype).name if np.dtype(dtype).name != 'bool_' else 'bool'


def is_floating(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.inexact)
