"""Dtype aliases with Paddle's names, backed by JAX dtypes.

Reference: python/paddle/framework/dtype.py (paddle.float32 etc.).
"""
import jax.numpy as jnp
import numpy as np

bool = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    'bool': bool, 'uint8': uint8, 'int8': int8, 'int16': int16,
    'int32': int32, 'int64': int64, 'float16': float16,
    'bfloat16': bfloat16, 'float32': float32, 'float64': float64,
    'complex64': complex64, 'complex128': complex128,
}

# TPU-native canonicalization: jax_enable_x64 is OFF (64-bit constants break
# Mosaic lowering and double HBM traffic for indices). Paddle's int64/float64
# API dtypes are accepted everywhere but canonicalize to their 32-bit
# counterparts at this boundary, matching XLA's own canonicalization —
# silently, with no per-call JAX warning.
_CANON64 = {
    np.dtype(np.int64): int32,
    np.dtype(np.uint64): jnp.uint32,
    np.dtype(np.float64): float32,
    np.dtype(np.complex128): complex64,
}


def convert_dtype(dtype):
    """Normalize a string / numpy / jax dtype spec to a numpy dtype-like."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        d = _STR2DTYPE[dtype]
    elif hasattr(dtype, 'dtype'):
        d = dtype
    else:
        d = np.dtype(dtype).type
    import jax
    if not jax.config.jax_enable_x64:
        d = _CANON64.get(np.dtype(d), d)
    return d


def dtype_name(dtype):
    return np.dtype(dtype).name if np.dtype(dtype).name != 'bool_' else 'bool'


def is_floating(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.inexact)
