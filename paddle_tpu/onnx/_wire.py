"""Minimal protobuf wire-format writer/reader (original implementation from
the public wire-format spec: varints, field tag = (number << 3) | wire_type,
length-delimited submessages). Enough to emit and re-read ONNX ModelProto
without the ``onnx`` or ``protobuf``-generated bindings.

Messages are represented as plain dicts: {field_number: value-or-list}. The
schema (which fields are submessages vs scalars) lives at the call site
(_schema.py); the reader returns raw bytes for length-delimited fields and
the caller decides whether to recurse.
"""
import struct


def _varint(n):
    out = bytearray()
    n &= (1 << 64) - 1                    # two's-complement for negatives
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field, wire_type):
    return _varint((field << 3) | wire_type)


def emit_varint(field, value):
    return tag(field, 0) + _varint(int(value))


def emit_bytes(field, data):
    if isinstance(data, str):
        data = data.encode('utf-8')
    return tag(field, 2) + _varint(len(data)) + bytes(data)


def emit_message(field, encoded):
    return emit_bytes(field, encoded)


def emit_float(field, value):
    return tag(field, 5) + struct.pack('<f', float(value))


def emit_packed_varints(field, values):
    payload = b''.join(_varint(int(v)) for v in values)
    return emit_bytes(field, payload)


# ---- reading ---------------------------------------------------------------

def read_varint(buf, pos):
    shift = result = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def parse(buf):
    """-> {field_number: [raw values]} ; wire-type 0 values are ints,
    wire-type 2 are bytes, wire-type 5 are 4-byte buffers."""
    fields = {}
    pos = 0
    while pos < len(buf):
        key, pos = read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            val, pos = read_varint(buf, pos)
        elif wt == 2:
            ln, pos = read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f'unsupported wire type {wt}')
        fields.setdefault(field, []).append(val)
    return fields


def unpack_varints(data):
    out, pos = [], 0
    while pos < len(data):
        v, pos = read_varint(data, pos)
        out.append(v)
    return out


def to_signed(v, bits=64):
    return v - (1 << bits) if v >= (1 << (bits - 1)) else v
