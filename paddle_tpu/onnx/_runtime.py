"""Reference ONNX executor for the exported op subset.

Runs a parsed ModelProto with numpy/jax — independent of the exporter's
jaxpr walk, so exporter↔runtime agreement is a real graph-semantics check
(and users without onnxruntime can still smoke-test exported models)."""
import numpy as np

from . import _proto as P


def _np_conv(x, w, attrs):
    import jax
    return np.asarray(jax.lax.conv_general_dilated(
        x, w,
        window_strides=attrs.get('strides', [1] * (x.ndim - 2)),
        padding=list(zip(attrs.get('pads', [0] * 2 * (x.ndim - 2))
                         [:x.ndim - 2],
                         attrs.get('pads', [0] * 2 * (x.ndim - 2))
                         [x.ndim - 2:])),
        rhs_dilation=attrs.get('dilations', [1] * (x.ndim - 2)),
        feature_group_count=attrs.get('group', 1),
        dimension_numbers=('NCHW', 'OIHW', 'NCHW')[:3]
        if x.ndim == 4 else None))


def _pool(x, attrs, kind):
    import jax
    k = attrs['kernel_shape']
    s = attrs.get('strides', [1] * len(k))
    pads = attrs.get('pads', [0] * 2 * len(k))
    pad = [(0, 0), (0, 0)] + list(zip(pads[:len(k)], pads[len(k):]))
    wd = [1, 1] + list(k)
    ws = [1, 1] + list(s)
    if kind == 'max':
        init, op = -np.inf, jax.lax.max
    else:
        init, op = 0.0, jax.lax.add
    out = jax.lax.reduce_window(x, np.asarray(init, x.dtype), op, wd, ws,
                                pad)
    if kind == 'avg':
        out = out / np.prod(k)
    return np.asarray(out)


def run_model(parsed_or_bytes, inputs):
    """Execute the graph. inputs: dict name->array or positional list."""
    m = (parsed_or_bytes if isinstance(parsed_or_bytes, dict)
         else P.parse_model(parsed_or_bytes))
    env = dict(m['initializers'])
    if isinstance(inputs, (list, tuple)):
        inputs = dict(zip(m['inputs'], inputs))
    env.update({k: np.asarray(v) for k, v in inputs.items()})

    for nd in m['nodes']:
        op = nd['op_type']
        a = nd['attrs']
        x = [env[i] for i in nd['inputs']]
        if op == 'Identity':
            r = x[0]
        elif op in ('Add', 'Sub', 'Mul', 'Div', 'Pow'):
            f = {'Add': np.add, 'Sub': np.subtract, 'Mul': np.multiply,
                 'Div': np.divide, 'Pow': np.power}[op]
            r = f(x[0], x[1])
        elif op in ('Max', 'Min'):
            r = (np.maximum if op == 'Max' else np.minimum)(*x)
        elif op == 'Mod':
            r = (np.fmod if a.get('fmod') else np.mod)(x[0], x[1])
        elif op == 'Relu':
            r = np.maximum(x[0], 0)
        elif op in ('Exp', 'Log', 'Tanh', 'Abs', 'Sqrt', 'Floor',
                    'Ceil', 'Sign', 'Sin', 'Cos'):
            r = getattr(np, op.lower())(x[0])
        elif op == 'Neg':
            r = np.negative(x[0])                 # numpy spells it negative
        elif op == 'Sigmoid':
            r = 1.0 / (1.0 + np.exp(-x[0]))
        elif op == 'Erf':
            import jax.scipy.special as _jsp      # no scipy dep in-image
            r = np.asarray(_jsp.erf(x[0]))
        elif op == 'Reciprocal':
            r = 1.0 / x[0]
        elif op in ('And', 'Or', 'Not'):
            f = {'And': np.logical_and, 'Or': np.logical_or,
                 'Not': np.logical_not}[op]
            r = f(*x)
        elif op in ('Less', 'LessOrEqual', 'Greater', 'GreaterOrEqual',
                    'Equal'):
            f = {'Less': np.less, 'LessOrEqual': np.less_equal,
                 'Greater': np.greater, 'GreaterOrEqual': np.greater_equal,
                 'Equal': np.equal}[op]
            r = f(x[0], x[1])
        elif op == 'Where':
            r = np.where(x[0], x[1], x[2])
        elif op == 'Cast':
            r = x[0].astype(P.DTYPES_INV[a['to']])
        elif op == 'Reshape':
            r = x[0].reshape([int(d) for d in x[1]])
        elif op == 'Expand':
            # ONNX Expand broadcasts BIDIRECTIONALLY (a target dim of 1
            # keeps the input dim) — np.broadcast_to alone is one-way and
            # rejects a dynamic batch flowing through a traced-1 target
            tgt = np.broadcast_shapes(x[0].shape,
                                      tuple(int(d) for d in x[1]))
            r = np.broadcast_to(x[0], tgt).copy()
        elif op == 'Transpose':
            r = np.transpose(x[0], a['perm'])
        elif op == 'Concat':
            r = np.concatenate(x, axis=a['axis'])
        elif op == 'Slice':
            data, starts, ends, axes, steps = x
            sl = [slice(None)] * data.ndim
            for st, en, ax, sp in zip(starts, ends, axes, steps):
                sl[int(ax)] = slice(int(st), int(en), int(sp))
            r = data[tuple(sl)]
        elif op == 'Pad':
            data, pads, cval = x
            n = data.ndim
            width = [(int(pads[i]), int(pads[n + i])) for i in range(n)]
            r = np.pad(data, width, constant_values=cval)
        elif op == 'Gather':
            r = np.take(x[0], x[1].astype(np.int64), axis=a.get('axis', 0))
        elif op == 'MatMul':
            r = np.matmul(x[0], x[1])
        elif op == 'ReduceSum':
            axes = tuple(int(d) for d in x[1]) if len(x) > 1 else None
            r = np.sum(x[0], axis=axes,
                       keepdims=bool(a.get('keepdims', 1)))
        elif op in ('ReduceMax', 'ReduceMin', 'ReduceProd'):
            f = {'ReduceMax': np.max, 'ReduceMin': np.min,
                 'ReduceProd': np.prod}[op]
            r = f(x[0], axis=tuple(a['axes']),
                  keepdims=bool(a.get('keepdims', 1)))
        elif op == 'Conv':
            r = _np_conv(x[0], x[1], a)
        elif op == 'MaxPool':
            r = _pool(x[0], a, 'max')
        elif op == 'AveragePool':
            r = _pool(x[0], a, 'avg')
        elif op == 'TopK':
            axis = a.get('axis', -1)
            k = int(np.asarray(x[1]).reshape(-1)[0])
            key = -x[0] if a.get('largest', 1) else x[0]
            idx = np.argsort(key, axis=axis, kind='stable')
            idx = np.take(idx, np.arange(k), axis=axis)
            vals = np.take_along_axis(x[0], idx, axis=axis)
            r = [vals, idx.astype(np.int64)]
        elif op == 'GatherElements':
            r = np.take_along_axis(x[0], x[1].astype(np.int64),
                                   axis=a.get('axis', 0))
        elif op == 'ScatterND':
            data, idx, upd = x
            r = data.copy()
            idx = idx.astype(np.int64)
            for i in range(idx.shape[0]):
                r[tuple(idx[i])] = upd[i]
        else:
            raise NotImplementedError(f'reference runtime: op {op}')
        outs = r if isinstance(r, list) else [r]
        for o_name, val in zip(nd['outputs'], outs):
            env[o_name] = np.asarray(val)

    return [env[o] for o in m['outputs']]
