"""paddle.onnx: real ONNX export (round 4; SURVEY row 51).

Reference: python/paddle/onnx/export.py:105 (delegates to the external
paddle2onnx package, which walks the ProgramDesc). TPU-native: the traced
jaxpr IS the program, so ``export`` walks it directly and emits a
self-contained .onnx ModelProto — hand-encoded wire format, no ``onnx``
package dependency — covering the model-zoo op subset (matmul/conv/pool/
norm/activations/shape ops). See onnx/_export.py for the op table.

``jit.save``'s StableHLO + ``.pdexec`` artifacts remain the native serving
interchange (inference.create_predictor); ONNX is the cross-ecosystem exit.
``reference_run`` executes an exported model with the bundled reference
runtime so exports can be validated without onnxruntime.
"""
import numpy as np

from ._export import Exporter, OnnxExportError  # noqa: F401
from ._proto import parse_model  # noqa: F401
from ._runtime import run_model as reference_run  # noqa: F401


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Export ``layer`` to ``<path>.onnx`` (plus the native StableHLO/
    .pdexec artifacts via jit.save, matching the reference's behaviour of
    producing a deployable bundle)."""
    import jax
    import jax.numpy as jnp

    from .. import jit as jit_mod
    from ..nn.layer_base import (buffer_arrays, functional_call,
                                 param_arrays)
    from ..static import InputSpec

    base = path[:-len('.onnx')] if path.endswith('.onnx') else path
    for spec in input_spec or []:
        if (isinstance(spec, InputSpec)
                and any(d in (None, -1) for d in list(spec.shape)[1:])):
            raise ValueError(
                'only the LEADING (batch) dim may be dynamic in an ONNX '
                f'export; got InputSpec shape {list(spec.shape)}')
    jit_mod.save(layer, base, input_spec=input_spec)

    if input_spec is None:
        raise ValueError('onnx.export requires input_spec (the reference '
                         'requires it for the same reason: the graph is '
                         'traced at export time)')
    xs = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            shape = [1 if d in (None, -1) else int(d) for d in spec.shape]
            xs.append(jnp.zeros(shape, jnp.dtype(spec.dtype)))
        else:
            xs.append(jnp.asarray(spec))

    was_training = getattr(layer, 'training', False)
    layer.eval()
    try:
        params, buffers = param_arrays(layer), buffer_arrays(layer)

        def fwd(params, buffers, *xs):
            out, _ = functional_call(layer, params, buffers, *xs)
            return out

        closed = jax.make_jaxpr(fwd)(params, buffers, *xs)
    finally:
        if was_training:
            layer.train()
    jaxpr = closed.jaxpr

    n_param = len(jax.tree_util.tree_leaves(params))
    n_buf = len(jax.tree_util.tree_leaves(buffers))
    weight_vars = jaxpr.invars[:n_param + n_buf]
    input_vars = jaxpr.invars[n_param + n_buf:]

    ex = Exporter(graph_name=type(layer).__name__)
    for cv, c in zip(jaxpr.constvars, closed.consts):
        ex.const_vals[cv] = np.asarray(c)
    flat_w = (jax.tree_util.tree_leaves(params)
              + jax.tree_util.tree_leaves(buffers))
    for var, val in zip(weight_vars, flat_w):
        ex.const_vals[var] = np.asarray(val)
    spec_shapes = [list(s.shape) if isinstance(s, InputSpec)
                   else list(np.asarray(s).shape) for s in input_spec]
    model_bytes = ex.build(jaxpr, input_vars,
                           [f'input_{i}' for i in range(len(input_vars))],
                           opset=opset_version, input_shapes=spec_shapes)
    out_path = base + '.onnx'
    with open(out_path, 'wb') as f:
        f.write(model_bytes)
    return out_path
