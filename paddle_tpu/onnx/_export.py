"""jaxpr -> ONNX graph conversion.

Reference: python/paddle/onnx/export.py:105 (delegates to paddle2onnx, which
walks the ProgramDesc op by op). TPU-native: the traced jaxpr IS the program,
so the exporter walks its equations, const-folds anything computable at
export time (iota, shape math, eval-mode batchnorm constants), and emits ONNX
nodes for the data-path primitives of the model zoo: matmul family, conv,
pooling, elementwise, reductions, shape ops, select/compare, cast, gather.

Inner jit/custom_vjp/remat calls are inlined. Unsupported primitives raise
OnnxExportError naming the op so the scope is explicit.
"""
import numpy as np

import jax
from jax.extend.core import Literal

from . import _proto as P


class OnnxExportError(NotImplementedError):
    pass


_ELEMENTWISE = {
    'add': 'Add', 'sub': 'Sub', 'mul': 'Mul', 'div': 'Div', 'pow': 'Pow',
    'max': 'Max', 'min': 'Min',
    'exp': 'Exp', 'log': 'Log', 'tanh': 'Tanh', 'logistic': 'Sigmoid',
    'neg': 'Neg', 'abs': 'Abs', 'sqrt': 'Sqrt', 'sign': 'Sign',
    'floor': 'Floor', 'ceil': 'Ceil', 'erf': 'Erf',
    'sin': 'Sin', 'cos': 'Cos', 'stop_gradient': 'Identity',
    'copy': 'Identity', 'and': 'And', 'or': 'Or', 'not': 'Not',
}
_COMPARE = {'lt': 'Less', 'le': 'LessOrEqual', 'gt': 'Greater',
            'ge': 'GreaterOrEqual', 'eq': 'Equal'}
_REDUCE_ATTR = {'reduce_max': 'ReduceMax', 'reduce_min': 'ReduceMin',
                'reduce_prod': 'ReduceProd'}


def _shape(atom):
    return tuple(int(d) for d in atom.aval.shape)


class Exporter:
    def __init__(self, graph_name='paddle_tpu_graph'):
        self.graph_name = graph_name
        self.nodes = []
        self.initializers = {}          # name -> ndarray
        self.const_vals = {}            # var -> ndarray (foldable)
        self.names = {}                 # var -> str
        self._uid = 0

    # ---- naming / values ------------------------------------------------
    def _fresh(self, hint='t'):
        self._uid += 1
        return f'{hint}_{self._uid}'

    def name_of(self, atom):
        if isinstance(atom, Literal):
            return self.add_const(np.asarray(atom.val))
        if atom in self.const_vals and atom not in self.names:
            self.names[atom] = self.add_const(self.const_vals[atom])
        if atom not in self.names:
            self.names[atom] = self._fresh('v')
        return self.names[atom]

    def add_const(self, arr, hint='c'):
        arr = np.asarray(arr)
        name = self._fresh(hint)
        self.initializers[name] = arr
        return name

    def emit(self, op, inputs, n_out=1, **attrs):
        outs = [self._fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(P.node(op, inputs, outs, **attrs))
        return outs[0] if n_out == 1 else outs

    def _is_const(self, atom):
        return isinstance(atom, Literal) or atom in self.const_vals

    def _const_of(self, atom, what='operand'):
        if isinstance(atom, Literal):
            return np.asarray(atom.val)
        if atom in self.const_vals:
            return np.asarray(self.const_vals[atom])
        raise OnnxExportError(f'{what} must be export-time constant')

    # ---- main walk ------------------------------------------------------
    def run(self, jaxpr):
        for eqn in jaxpr.eqns:
            # const folding: every input known -> evaluate now
            if all(self._is_const(v) for v in eqn.invars) \
                    and eqn.primitive.name not in ('jit', 'pjit', 'scan',
                                                   'while', 'cond'):
                try:
                    vals = [self._const_of(v) for v in eqn.invars]
                    outs = eqn.primitive.bind(
                        *[jax.numpy.asarray(v) for v in vals], **eqn.params)
                    outs = outs if eqn.primitive.multiple_results else [outs]
                    for var, val in zip(eqn.outvars, outs):
                        self.const_vals[var] = np.asarray(val)
                    continue
                except Exception:
                    pass                         # fall through to node emit
            self._eqn(eqn)

    def _inline(self, eqn):
        if eqn.primitive.name in ('scan', 'while', 'cond', 'fori_loop'):
            # inlining a loop body would execute it ONCE — silently wrong.
            # Structured control flow needs ONNX Loop/If emission (not
            # implemented); refuse loudly.
            raise OnnxExportError(
                f'primitive {eqn.primitive.name!r} (structured control '
                'flow) is not supported by the ONNX exporter — unroll the '
                'loop in the model (e.g. GPTConfig(scan_layers=False)-style '
                'stacking) or export via StableHLO/jit.save instead')
        inner = None
        for key in ('jaxpr', 'call_jaxpr', 'fun_jaxpr'):
            inner = eqn.params.get(key)
            if inner is not None:
                break
        if inner is None:
            raise OnnxExportError(
                f'primitive {eqn.primitive.name!r} not supported by the '
                'ONNX exporter')
        jaxpr = inner.jaxpr if hasattr(inner, 'jaxpr') else inner
        consts = getattr(inner, 'consts', [])
        n = len(jaxpr.invars)
        outer_in = eqn.invars[-n:]
        # jit caches one traced jaxpr per function, so a second call site
        # shares the SAME body Var objects: scrub every binding the previous
        # inline left behind (invars and eqn outvars) or this call would
        # fold with the previous call's constants
        def scrub(jx):
            for v in jx.invars:
                self.const_vals.pop(v, None)
                self.names.pop(v, None)
            for e in jx.eqns:
                for ov in e.outvars:
                    self.const_vals.pop(ov, None)
                    self.names.pop(ov, None)
        scrub(jaxpr)
        for cv, c in zip(jaxpr.constvars, consts):
            self.const_vals[cv] = np.asarray(c)
        for bi, oi in zip(jaxpr.invars, outer_in):
            if isinstance(oi, Literal):       # Literal is unhashable: check
                self.const_vals[bi] = np.asarray(oi.val)   # before dict use
            elif oi in self.const_vals:
                self.const_vals[bi] = self.const_vals[oi]
            else:
                self.names[bi] = self.name_of(oi)
        self.run(jaxpr)
        for bo, oo in zip(jaxpr.outvars, eqn.outvars):
            if bo in self.const_vals:
                self.const_vals[oo] = self.const_vals[bo]
            else:
                self.names[oo] = self.name_of(bo)

    # ---- one equation ---------------------------------------------------
    def _eqn(self, eqn):
        name = eqn.primitive.name
        out = eqn.outvars[0]

        if name in _ELEMENTWISE:
            got = self.emit(_ELEMENTWISE[name],
                            [self.name_of(v) for v in eqn.invars])
            self.names[out] = got
        elif name in _COMPARE:
            self.names[out] = self.emit(
                _COMPARE[name], [self.name_of(v) for v in eqn.invars])
        elif name == 'ne':
            eqv = self.emit('Equal', [self.name_of(v) for v in eqn.invars])
            self.names[out] = self.emit('Not', [eqv])
        elif name == 'rem':
            # lax.rem truncates toward zero (sign of dividend): ONNX Mod
            # needs fmod=1 for those semantics (fmod=0 follows the divisor)
            self.names[out] = self.emit(
                'Mod', [self.name_of(v) for v in eqn.invars], fmod=1)
        elif name == 'rsqrt':
            s = self.emit('Sqrt', [self.name_of(eqn.invars[0])])
            self.names[out] = self.emit('Reciprocal', [s])
        elif name == 'erfc':
            # erfc(x) = 1 - erf(x) (exact-GELU lowers through erfc)
            e = self.emit('Erf', [self.name_of(eqn.invars[0])])
            one = self.add_const(np.asarray(1, eqn.invars[0].aval.dtype))
            self.names[out] = self.emit('Sub', [one, e])
        elif name == 'square':
            x = self.name_of(eqn.invars[0])
            self.names[out] = self.emit('Mul', [x, x])
        elif name == 'integer_pow':
            x = self.name_of(eqn.invars[0])
            y = eqn.params['y']
            if y == 2:
                self.names[out] = self.emit('Mul', [x, x])
            else:
                c = self.add_const(
                    np.asarray(y, eqn.invars[0].aval.dtype))
                self.names[out] = self.emit('Pow', [x, c])
        elif name == 'select_n':
            pred, *cases = eqn.invars
            if len(cases) != 2:
                raise OnnxExportError('select_n with >2 cases')
            self.names[out] = self.emit(
                'Where', [self.name_of(pred), self.name_of(cases[1]),
                          self.name_of(cases[0])])
        elif name == 'convert_element_type':
            to = P.DTYPES[np.dtype(eqn.params['new_dtype'])]
            self.names[out] = self.emit(
                'Cast', [self.name_of(eqn.invars[0])], to=to)
        elif name in ('reshape', 'squeeze'):
            self.names[out] = self.emit(
                'Reshape', [self.name_of(eqn.invars[0]),
                            self._dyn0_shape(_shape(out))])
        elif name == 'transpose':
            self.names[out] = self.emit(
                'Transpose', [self.name_of(eqn.invars[0])],
                perm=list(eqn.params['permutation']))
        elif name == 'broadcast_in_dim':
            x = self.name_of(eqn.invars[0])
            bcd = eqn.params['broadcast_dimensions']
            mid = [1] * len(_shape(out))
            for i, od in enumerate(bcd):
                mid[od] = _shape(eqn.invars[0])[i]
            x = self.emit('Reshape', [x, self._dyn0_shape(mid)])
            # Expand target stays static: ONNX Expand BROADCASTS (a target
            # dim of 1 keeps the input dim), so a dynamic batch flowing
            # through the input survives a traced-batch-1 target
            shp = self.add_const(np.asarray(_shape(out), np.int64))
            self.names[out] = self.emit('Expand', [x, shp])
        elif name == 'concatenate':
            self.names[out] = self.emit(
                'Concat', [self.name_of(v) for v in eqn.invars],
                axis=int(eqn.params['dimension']))
        elif name == 'slice':
            starts = list(eqn.params['start_indices'])
            ends = list(eqn.params['limit_indices'])
            steps = list(eqn.params['strides'] or
                         [1] * len(starts))
            in_sh = _shape(eqn.invars[0])
            if getattr(self, '_dyn0', False):
                # The dynamic batch traces at size 1 but can sit at ANY dim
                # position (e.g. seq-major after a transpose), so guard every
                # traced-size-1 dim: a full pass-through gets INT64_MAX
                # ("to the end" in ONNX) — correct whether the dim is the
                # dynamic batch or genuinely size 1 — while an end baked to
                # the traced 1 would silently DROP rows at runtime (review
                # r4). Trace-at-1 ambiguity: a literal [:1] slice on the
                # batch is indistinguishable from [:B] and exports as the
                # latter. Dims traced >1 cannot be the batch and slice
                # statically.
                for dim, sz in enumerate(in_sh):
                    if sz != 1:
                        continue
                    if (starts[dim] == 0 and ends[dim] == 1
                            and steps[dim] == 1):
                        ends[dim] = np.iinfo(np.int64).max
                    else:
                        raise OnnxExportError(
                            'slicing a sub-range of a traced-size-1 axis '
                            f'(dim {dim}) is ambiguous under a dynamic '
                            'batch — export with a static batch InputSpec '
                            'instead')
            ins = [self.name_of(eqn.invars[0]),
                   self.add_const(np.asarray(starts, np.int64)),
                   self.add_const(np.asarray(ends, np.int64)),
                   self.add_const(np.asarray(range(len(starts)), np.int64)),
                   self.add_const(np.asarray(steps, np.int64))]
            self.names[out] = self.emit('Slice', ins)
        elif name == 'pad':
            lo_hi_int = eqn.params['padding_config']
            if any(i != 0 for _, _, i in lo_hi_int):
                raise OnnxExportError('interior (dilating) pad')
            pads = ([lo for lo, _, _ in lo_hi_int]
                    + [hi for _, hi, _ in lo_hi_int])
            if any(p < 0 for p in pads):
                raise OnnxExportError('negative pad (use slice)')
            cval = self._const_of(eqn.invars[1], 'pad value')
            ins = [self.name_of(eqn.invars[0]),
                   self.add_const(np.asarray(pads, np.int64)),
                   self.add_const(np.asarray(cval))]
            self.names[out] = self.emit('Pad', ins, mode='constant')
        elif name == 'reduce_sum':
            axes = self.add_const(
                np.asarray(eqn.params['axes'], np.int64))
            self.names[out] = self.emit(
                'ReduceSum', [self.name_of(eqn.invars[0]), axes],
                keepdims=0)
        elif name in _REDUCE_ATTR:
            self.names[out] = self.emit(
                _REDUCE_ATTR[name], [self.name_of(eqn.invars[0])],
                axes=list(eqn.params['axes']), keepdims=0)
        elif name == 'dot_general':
            self._dot(eqn)
        elif name == 'conv_general_dilated':
            self._conv(eqn)
        elif name in ('reduce_window_max', 'reduce_window_sum'):
            self._pool(eqn)
        elif name == 'gather':
            self._gather(eqn)
        elif name == 'iota':
            # static shape: materialize (normally reached via const folding,
            # kept for safety)
            d = eqn.params['dimension']
            shape = _shape(out)
            arr = np.broadcast_to(
                np.arange(shape[d]).reshape(
                    [-1 if i == d else 1 for i in range(len(shape))]),
                shape).astype(np.dtype(eqn.params['dtype']))
            self.const_vals[out] = arr
        elif name == 'sort':
            self._sort(eqn)
        elif name == 'dynamic_slice':
            self._dynamic_slice(eqn)
        elif name == 'dynamic_update_slice':
            self._dynamic_update_slice(eqn)
        else:
            self._inline(eqn)

    # ---- sorting / dynamic indexing (r5: static-NMS detector export) ----

    def _starts_tensor(self, start_vars):
        """Scalar start operands -> one int64 [n] tensor (runtime values
        allowed: each scalar is reshaped to [1], cast, concatenated)."""
        parts = []
        for sv in start_vars:
            nm = self.emit('Reshape', [self.name_of(sv),
                                       self.add_const(
                                           np.asarray([1], np.int64))])
            parts.append(self.emit('Cast', [nm], to=P.DTYPES[np.dtype(
                np.int64)]))
        if len(parts) == 1:
            return parts[0]
        return self.emit('Concat', parts, axis=0)

    def _sort(self, eqn):
        """lax.sort (ascending, 1 key) -> TopK(largest=0, K=dim size);
        carried operands ride the permutation via GatherElements. Tie
        order is runtime-defined (jax is stable) — detector NMS sorts
        distinct scores, where this cannot matter."""
        if eqn.params.get('num_keys', 1) != 1:
            raise OnnxExportError('sort with num_keys > 1 not exported')
        dim = eqn.params['dimension']
        size = _shape(eqn.invars[0])[dim]
        k = self.add_const(np.asarray([size], np.int64))
        vals, idx = self.emit('TopK', [self.name_of(eqn.invars[0]), k],
                              n_out=2, axis=dim, largest=0, sorted=1)
        self.names[eqn.outvars[0]] = vals
        for op_v, out_v in zip(eqn.invars[1:], eqn.outvars[1:]):
            self.names[out_v] = self.emit(
                'GatherElements', [self.name_of(op_v), idx], axis=dim)

    def _dynamic_slice(self, eqn):
        """lax.dynamic_slice with (possibly runtime) scalar starts ->
        Slice with tensor starts/ends. jax's OOB-start clamping is NOT
        reproduced — exported graphs must keep starts in range (the
        static-NMS sweep does by construction)."""
        operand, start_vars = eqn.invars[0], eqn.invars[1:]
        sizes = np.asarray(eqn.params['slice_sizes'], np.int64)
        starts = self._starts_tensor(start_vars)
        ends = self.emit('Add', [starts, self.add_const(sizes)])
        axes = self.add_const(np.arange(len(sizes), dtype=np.int64))
        steps = self.add_const(np.ones(len(sizes), np.int64))
        self.names[eqn.outvars[0]] = self.emit(
            'Slice', [self.name_of(operand), starts, ends, axes, steps])

    def _dynamic_update_slice(self, eqn):
        """1-D lax.dynamic_update_slice -> ScatterND with runtime start
        (indices = start + arange(len(update))). The NMS keep-array write
        is the motivating case; higher ranks raise."""
        operand, update = eqn.invars[0], eqn.invars[1]
        if len(_shape(operand)) != 1:
            raise OnnxExportError(
                'dynamic_update_slice exported for 1-D operands only')
        L = _shape(update)[0]
        start = self._starts_tensor(eqn.invars[2:3])
        idx = self.emit('Add', [
            self.add_const(np.arange(L, dtype=np.int64)[:, None]),
            self.emit('Reshape', [start, self.add_const(
                np.asarray([1, 1], np.int64))])])
        self.names[eqn.outvars[0]] = self.emit(
            'ScatterND', [self.name_of(operand), idx,
                          self.name_of(update)])

    def _dyn0_shape(self, shape):
        """Reshape target with the leading dim emitted as -1 (inferred).

        The graph is traced at batch=1, so baking the traced leading dim
        into Reshape targets breaks dynamic-batch inference (journey r4:
        MatMul operand flattens carried a literal batch). Guards (review
        r4): only when the export requested a dynamic batch, and only when
        the traced leading dim IS the traced batch value 1 — a reshape
        whose leading dim is some other size (e.g. seq-major flatten)
        stays static and fails loudly at runtime rather than silently
        mis-reshaping."""
        t = list(int(d) for d in shape)
        if t and t[0] == 1 and getattr(self, '_dyn0', False):
            t[0] = -1
        return self.add_const(np.asarray(t, np.int64))

    # ---- structured ops -------------------------------------------------
    def _dot(self, eqn):
        lhs, rhs = eqn.invars
        (lc, rc), (lb, rb) = eqn.params['dimension_numbers']
        lsh, rsh = _shape(lhs), _shape(rhs)
        l_free = [d for d in range(len(lsh)) if d not in lc and d not in lb]
        r_free = [d for d in range(len(rsh)) if d not in rc and d not in rb]
        ln, rn = self.name_of(lhs), self.name_of(rhs)

        l_perm = list(lb) + l_free + list(lc)
        r_perm = list(rb) + list(rc) + r_free
        if l_perm != list(range(len(lsh))):
            ln = self.emit('Transpose', [ln], perm=l_perm)
        if r_perm != list(range(len(rsh))):
            rn = self.emit('Transpose', [rn], perm=r_perm)
        k = int(np.prod([lsh[d] for d in lc], dtype=np.int64)) if lc else 1
        m = int(np.prod([lsh[d] for d in l_free], dtype=np.int64))
        n = int(np.prod([rsh[d] for d in r_free], dtype=np.int64))
        batch = [lsh[d] for d in lb]
        l_tgt = batch + [m, k]
        if (getattr(self, '_dyn0', False) and not lb and l_free
                and l_free[0] == 0):
            # the rows slot MERGES the leading batch with other free dims
            # (m = B * ...), so it must be inferred even when m != 1 —
            # e.g. Embedding output [B,S,E] flattening to [B*S, E]
            l_tgt = [-1, k]
            ln_shaped = self.add_const(np.asarray(l_tgt, np.int64))
        else:
            ln_shaped = self._dyn0_shape(l_tgt)
        ln = self.emit('Reshape', [ln, ln_shaped])
        rn = self.emit('Reshape', [rn, self._dyn0_shape(batch + [k, n])])
        mm = self.emit('MatMul', [ln, rn])
        self.names[eqn.outvars[0]] = self.emit(
            'Reshape', [mm, self._dyn0_shape(_shape(eqn.outvars[0]))])

    def _conv(self, eqn):
        lhs, rhs = eqn.invars
        dn = eqn.params['dimension_numbers']
        if any(d != 1 for d in eqn.params['lhs_dilation']):
            raise OnnxExportError('transposed conv (lhs_dilation)')
        x = self.name_of(lhs)
        wgt = self.name_of(rhs)
        lspec, rspec, ospec = dn.lhs_spec, dn.rhs_spec, dn.out_spec
        if list(lspec) != list(range(len(lspec))):
            x = self.emit('Transpose', [x], perm=list(lspec))
        if list(rspec) != list(range(len(rspec))):
            wgt = self.emit('Transpose', [wgt], perm=list(rspec))
        pads = ([lo for lo, _ in eqn.params['padding']]
                + [hi for _, hi in eqn.params['padding']])
        conv = self.emit(
            'Conv', [x, wgt],
            strides=list(eqn.params['window_strides']),
            pads=pads,
            dilations=list(eqn.params['rhs_dilation']),
            group=int(eqn.params['feature_group_count']))
        inv = np.argsort(ospec).tolist()
        if inv != list(range(len(ospec))):
            conv = self.emit('Transpose', [conv], perm=inv)
        self.names[eqn.outvars[0]] = conv

    def _pool(self, eqn):
        name = eqn.primitive.name
        wd = list(eqn.params['window_dimensions'])
        ws = list(eqn.params['window_strides'])
        pad = list(eqn.params['padding'])
        if any(d != 1 for d in eqn.params.get('base_dilation', [1] * len(wd))
               ) or any(d != 1 for d in
                        eqn.params.get('window_dilation', [1] * len(wd))):
            raise OnnxExportError('dilated pooling window')
        pass_dims = [d for d in range(len(wd))
                     if wd[d] == 1 and ws[d] == 1 and pad[d] == (0, 0)]
        win_dims = [d for d in range(len(wd)) if d not in pass_dims]
        if len(pass_dims) != 2:
            raise OnnxExportError(
                f'pooling window over {len(win_dims)} dims with '
                f'{len(pass_dims)} passthrough dims (need N,C + spatial)')
        perm = pass_dims + win_dims
        x = self.name_of(eqn.invars[0])
        if perm != list(range(len(wd))):
            x = self.emit('Transpose', [x], perm=perm)
        kernel = [wd[d] for d in win_dims]
        pads = ([pad[d][0] for d in win_dims]
                + [pad[d][1] for d in win_dims])
        if name == 'reduce_window_max':
            pool = self.emit('MaxPool', [x], kernel_shape=kernel,
                             strides=[ws[d] for d in win_dims], pads=pads)
        else:
            # sum pool = AveragePool(count_include_pad) * window_size
            pool = self.emit('AveragePool', [x], kernel_shape=kernel,
                             strides=[ws[d] for d in win_dims], pads=pads,
                             count_include_pad=1)
            k = self.add_const(
                np.asarray(np.prod(kernel),
                           np.dtype(eqn.invars[0].aval.dtype)))
            pool = self.emit('Mul', [pool, k])
        inv = np.argsort(perm).tolist()
        if inv != list(range(len(wd))):
            pool = self.emit('Transpose', [pool], perm=inv)
        self.names[eqn.outvars[0]] = pool

    def _gather(self, eqn):
        operand, idx = eqn.invars
        dn = eqn.params['dimension_numbers']
        osh = _shape(operand)
        slice_sizes = list(eqn.params['slice_sizes'])
        # simple take(arr, idx, axis): ONE collapsed gathered dim, full
        # slices elsewhere, trailing index-vector dim of size 1
        if (len(dn.start_index_map) == 1
                and dn.collapsed_slice_dims == (dn.start_index_map[0],)
                and _shape(idx)[-1] == 1
                and all(slice_sizes[d] == osh[d]
                        for d in range(len(osh))
                        if d != dn.start_index_map[0])):
            axis = dn.start_index_map[0]
            idx_name = self.name_of(idx)
            ish = _shape(idx)[:-1]
            idx_name = self.emit('Reshape',
                                 [idx_name, self._dyn0_shape(ish)])
            self.names[eqn.outvars[0]] = self.emit(
                'Gather', [self.name_of(operand), idx_name], axis=axis)
        else:
            raise OnnxExportError('general gather (only take-style '
                                  'single-axis gathers are exported)')

    # ---- finish ---------------------------------------------------------
    def build(self, jaxpr, input_vars, input_names, opset=13,
              input_shapes=None):
        """input_shapes: optional per-input shapes with None for symbolic
        dims (from the user's InputSpec) — emitted as dim_param so ONNX
        consumers accept dynamic batches; traced dims otherwise."""
        inputs = []
        dyn_batch = False
        for idx, (var, iname) in enumerate(zip(input_vars, input_names)):
            self.names[var] = iname
            shape = _shape(var)
            if input_shapes is not None and idx < len(input_shapes):
                # non-leading dynamic dims were rejected up front by
                # onnx.export (the single validation point)
                spec = list(input_shapes[idx])
                if len(spec) == len(shape):
                    shape = [None if s in (None, -1) else d
                             for s, d in zip(spec, shape)]
                    dyn_batch = dyn_batch or None in shape
            inputs.append(P.value_info(iname, var.aval.dtype, shape))
        self._dyn0 = dyn_batch      # consulted by _dyn0_shape during run
        self.run(jaxpr)
        outputs = []
        for i, ov in enumerate(jaxpr.outvars):
            oname = self.name_of(ov)
            if ov in self.const_vals and oname in self.initializers:
                # constant output: route through Identity so it is a node
                oname = self.emit('Identity', [oname])
            oshape = list(_shape(ov))
            if dyn_batch and oshape and oshape[0] == 1:
                # traced batch was 1; a dynamic input batch flows through
                oshape[0] = None
            outputs.append(P.value_info(f'output_{i}', ov.aval.dtype,
                                        oshape))
            self.nodes.append(P.node('Identity', [oname], [f'output_{i}']))
        inits = [P.tensor(n, a) for n, a in self.initializers.items()]
        g = P.graph(self.nodes, self.graph_name, inits, inputs, outputs)
        return P.model(g, opset_version=opset)
