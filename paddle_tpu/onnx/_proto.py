"""ONNX ModelProto construction/parsing over the wire-format helpers.

Field numbers transcribed from the public onnx.proto schema (interface
facts). Only the subset the exporter emits is covered. Tensors use raw_data
(little-endian), the de-facto standard encoding.
"""
import numpy as np

from . import _wire as w

# TensorProto.DataType
DTYPES = {
    np.dtype('float32'): 1, np.dtype('uint8'): 2, np.dtype('int8'): 3,
    np.dtype('uint16'): 4, np.dtype('int16'): 5, np.dtype('int32'): 6,
    np.dtype('int64'): 7, np.dtype('bool'): 9, np.dtype('float16'): 10,
    np.dtype('float64'): 11, np.dtype('uint32'): 12, np.dtype('uint64'): 13,
}
DTYPES_INV = {v: k for k, v in DTYPES.items()}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_FLOATS, A_INTS, A_STRINGS = \
    1, 2, 3, 4, 6, 7, 8


def tensor(name, arr):
    arr = np.ascontiguousarray(arr)
    if arr.dtype == np.dtype('bool'):
        raw = arr.astype(np.uint8).tobytes()
    else:
        raw = arr.tobytes()
    out = b''.join(w.emit_varint(1, d) for d in arr.shape)
    out += w.emit_varint(2, DTYPES[arr.dtype])
    out += w.emit_bytes(8, name)
    out += w.emit_bytes(9, raw)
    return out


def attr(name, value):
    out = w.emit_bytes(1, name)
    if isinstance(value, float):
        out += w.emit_float(2, value) + w.emit_varint(20, A_FLOAT)
    elif isinstance(value, bool) or isinstance(value, (int, np.integer)):
        out += w.emit_varint(3, int(value)) + w.emit_varint(20, A_INT)
    elif isinstance(value, str):
        out += w.emit_bytes(4, value) + w.emit_varint(20, A_STRING)
    elif isinstance(value, (list, tuple)) and value and \
            isinstance(value[0], float):
        for v in value:
            out += w.emit_float(7, v)
        out += w.emit_varint(20, A_FLOATS)
    elif isinstance(value, (list, tuple)):
        for v in value:
            out += w.emit_varint(8, int(v))
        out += w.emit_varint(20, A_INTS)
    else:
        raise TypeError(f'attr {name}: unsupported {type(value)}')
    return out


def node(op_type, inputs, outputs, name='', **attrs):
    out = b''.join(w.emit_bytes(1, i) for i in inputs)
    out += b''.join(w.emit_bytes(2, o) for o in outputs)
    if name:
        out += w.emit_bytes(3, name)
    out += w.emit_bytes(4, op_type)
    for k, v in attrs.items():
        out += w.emit_message(5, attr(k, v))
    return out


def value_info(name, dtype, shape):
    dims = b''
    for d in shape:
        if isinstance(d, str) or d is None:
            dims += w.emit_message(1, w.emit_bytes(2, str(d or 'N')))
        else:
            dims += w.emit_message(1, w.emit_varint(1, int(d)))
    ttype = (w.emit_varint(1, DTYPES[np.dtype(dtype)])
             + w.emit_message(2, dims))
    return w.emit_bytes(1, name) + w.emit_message(2, w.emit_message(1, ttype))


def graph(nodes, name, initializers, inputs, outputs):
    out = b''.join(w.emit_message(1, n) for n in nodes)
    out += w.emit_bytes(2, name)
    out += b''.join(w.emit_message(5, t) for t in initializers)
    out += b''.join(w.emit_message(11, i) for i in inputs)
    out += b''.join(w.emit_message(12, o) for o in outputs)
    return out


def model(graph_bytes, opset_version=13, producer='paddle_tpu'):
    opset = w.emit_bytes(1, '') + w.emit_varint(2, opset_version)
    return (w.emit_varint(1, 8)                       # ir_version 8
            + w.emit_bytes(2, producer)
            + w.emit_message(7, graph_bytes)
            + w.emit_message(8, opset))


# ---- parsing (for the reference runtime + round-trip tests) ---------------

def _s(b):
    return b.decode('utf-8')


def parse_tensor(buf):
    f = w.parse(buf)
    dims = [w.to_signed(v) for v in f.get(1, [])]
    dt = DTYPES_INV[f[2][0]]
    name = _s(f[8][0]) if 8 in f else ''
    if 9 in f:
        raw = f[9][0]
        arr = (np.frombuffer(raw, np.uint8).astype(bool)
               if dt == np.dtype('bool')
               else np.frombuffer(raw, dt))
        arr = arr.reshape(dims)
    else:
        raise ValueError('tensor without raw_data')
    return name, arr


def parse_attr(buf):
    import struct
    f = w.parse(buf)
    name = _s(f[1][0])
    atype = f.get(20, [0])[0]
    if atype == A_FLOAT:
        return name, struct.unpack('<f', f[2][0])[0]
    if atype == A_INT:
        return name, w.to_signed(f[3][0])
    if atype == A_STRING:
        return name, _s(f[4][0])
    if atype == A_INTS:
        return name, [w.to_signed(v) for v in f.get(8, [])]
    if atype == A_FLOATS:
        return name, [struct.unpack('<f', v)[0] for v in f.get(7, [])]
    if atype == A_TENSOR:
        return name, parse_tensor(f[5][0])[1]
    raise ValueError(f'attr {name}: unsupported type {atype}')


def parse_node(buf):
    f = w.parse(buf)
    return {
        'inputs': [_s(b) for b in f.get(1, [])],
        'outputs': [_s(b) for b in f.get(2, [])],
        'op_type': _s(f[4][0]),
        'attrs': dict(parse_attr(a) for a in f.get(5, [])),
    }


def parse_value_info(buf):
    f = w.parse(buf)
    return _s(f[1][0])


def parse_model(buf):
    f = w.parse(buf)
    g = w.parse(f[7][0])
    return {
        'ir_version': f.get(1, [0])[0],
        'opset': [w.parse(o).get(2, [0])[0] for o in f.get(8, [])],
        'name': _s(g[2][0]) if 2 in g else '',
        'nodes': [parse_node(n) for n in g.get(1, [])],
        'initializers': dict(parse_tensor(t) for t in g.get(5, [])),
        'inputs': [parse_value_info(i) for i in g.get(11, [])],
        'outputs': [parse_value_info(o) for o in g.get(12, [])],
    }
