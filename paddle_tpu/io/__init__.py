"""Datasets, samplers, DataLoader.

Reference: python/paddle/io/__init__.py + fluid/reader.py + C++ data feeders.
The worker pool lives in native/dataloader.cpp (C++ threads + ring buffer);
Python falls back to synchronous iteration when the native lib is absent.
TPU twist: batches are host numpy, devices-put asynchronously (double
buffering) so the chip never waits on input.
"""
import itertools
import math
import time

import numpy as np

from ..core.tensor import Tensor
from ..tensor.random import next_key


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError('IterableDataset has no __getitem__')

    def __len__(self):
        raise RuntimeError('IterableDataset has no __len__')


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out = []
    off = 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num = num_samples

    @property
    def num_samples(self):
        return self._num if self._num is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, 'float64')
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the dataset across data-parallel ranks.
    Reference: python/paddle/io (fluid DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None:
            from ..distributed import get_world_size
            num_replicas = get_world_size()
        if rank is None:
            from ..distributed import get_rank
            rank = get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (→ Tensors)."""
    sample = batch[0]
    if isinstance(sample, (Tensor,)):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    import numbers
    if (isinstance(sample, numbers.Number)
            or isinstance(sample, (np.number, np.bool_))):
        # covers python ints/floats AND NUMERIC numpy scalars (np.float32(i)
        # is not a python float; np.str_/np.bytes_ must still fall through
        # untouched — a unicode array is not a Tensor)
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _host_collate_fn(batch):
    """default_collate_fn without the Tensor wrap: stacks to plain numpy.
    Built for background-thread collation — the consumer side of
    ``prefetch_to_device`` does one explicit device_put per array, so the
    producer must not touch the device at all."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    import numbers
    if (isinstance(sample, numbers.Number)
            or isinstance(sample, (np.number, np.bool_))):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        return tuple(_host_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: _host_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, prefetch_factor=2, use_shared_memory=True,
                 timeout=0, worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._iterable_mode = isinstance(dataset, IterableDataset)
        self.batch_size = batch_size
        self.drop_last = drop_last
        if not self._iterable_mode:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
        else:
            self.batch_sampler = None

    def __len__(self):
        if self._iterable_mode:
            raise TypeError('length of IterableDataset DataLoader unknown')
        return len(self.batch_sampler)

    def _fetch(self, i):
        """dataset[i] with up to 3 attempts — transient errors (flaky remote
        storage, a racy augmentation) retry with a short backoff instead of
        killing the epoch."""
        from ..fault import retry
        return retry(lambda: self.dataset[i], retries=3, backoff=0.05,
                     jitter=0.5)

    def _iter_sync(self):
        from .. import observability as _obs
        from ..fault.inject import inject
        batches = _obs.counter('data.batches')
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                inject('dataloader.step')
                batches.inc()
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                inject('dataloader.step')
                batches.inc()
                yield self.collate_fn([self._fetch(i) for i in idxs])

    def _warn_native(self, exc, what):
        if not getattr(self, '_native_warned', False):
            self._native_warned = True
            import warnings
            warnings.warn(
                f'native DataLoader worker pool {what} ({exc!r}); degrading '
                f'to synchronous iteration', RuntimeWarning, stacklevel=2)

    def _iter_native_fallback(self):
        """Native C++ worker pool with graceful degrade: if the pool cannot
        start or dies mid-epoch, finish the epoch synchronously from the
        first undelivered batch — one warning, no data loss."""
        from .. import observability as _obs
        from ..fault.inject import inject
        try:
            from .native_loader import NativeWorkerIterator
            it = NativeWorkerIterator(self)
        except Exception as e:
            self._warn_native(e, 'unavailable')
            yield from self._iter_sync()
            return
        batches = _obs.counter('data.batches')
        delivered = 0
        while True:
            try:
                batch = next(it)
            except StopIteration:
                return
            except Exception as e:
                self._warn_native(e, 'failed mid-epoch')
                for idxs in it.batches[delivered:]:
                    inject('dataloader.step')
                    batches.inc()
                    yield self.collate_fn([self._fetch(i) for i in idxs])
                return
            delivered += 1
            inject('dataloader.step')
            batches.inc()
            yield batch

    def __iter__(self):
        if self.num_workers > 0 and not self._iterable_mode:
            return self._iter_native_fallback()
        return self._iter_sync()

    def prefetch_to_device(self, n=2):
        """Double-buffered device prefetch: a background thread fetches and
        collates batch N+1 on the host while the consumer computes on batch
        N; each batch is explicitly device_put so the steady-state train
        loop performs no implicit host->device uploads. Yields the same
        (Tensor-wrapped) batches plain iteration would, in the same order.

        The batch index order is snapshotted on the CALLING thread — the
        samplers consume ``np.random`` state, which must stay on the main
        thread for AutoResume's deterministic per-epoch shuffle to replay.
        """
        import collections
        import queue
        import threading

        import jax

        from .. import observability as _obs
        from ..fault.inject import inject

        depth = max(1, int(n))
        if self._iterable_mode or self.num_workers > 0:
            host_iter = iter(self)
        else:
            batches = list(self.batch_sampler)
            host_collate = (_host_collate_fn
                            if self.collate_fn is default_collate_fn
                            else self.collate_fn)

            def _host_gen():
                collate_ms = _obs.histogram('data.collate_ms')
                n_batches = _obs.counter('data.batches')
                for idxs in batches:
                    inject('dataloader.step')
                    with _obs.span('data.host_collate',
                                   rows=len(idxs)) as sp:
                        b = host_collate([self._fetch(i) for i in idxs])
                    collate_ms.observe(1e3 * sp.duration)
                    n_batches.inc()
                    yield b

            host_iter = _host_gen()

        stop = threading.Event()
        q = queue.Queue(maxsize=depth)
        _END, _ERR = object(), object()

        def _put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def _produce():
            try:
                for b in host_iter:
                    if not _put((None, b)):
                        return
                _put((_END, None))
            except BaseException as e:   # relayed and re-raised by consumer
                _put((_ERR, e))

        def _to_device(b):
            if isinstance(b, Tensor):
                return Tensor(jax.device_put(np.asarray(b._value)))
            if isinstance(b, np.ndarray):
                return Tensor(jax.device_put(b))
            if isinstance(b, (list, tuple)):
                return type(b)(_to_device(x) for x in b)
            if isinstance(b, dict):
                return {k: _to_device(v) for k, v in b.items()}
            return b

        def _gen():
            thread = threading.Thread(target=_produce, daemon=True,
                                      name='prefetch_to_device')
            thread.start()
            pending = collections.deque()
            done = False
            device_put_ms = _obs.histogram('data.device_put_ms')
            prefetched = _obs.counter('data.prefetch_batches')
            try:
                while True:
                    # keep up to ``depth`` batches already on device so the
                    # next step's inputs are resident before dispatch
                    while not done and len(pending) < depth:
                        tag, payload = q.get()
                        if tag is _END:
                            done = True
                        elif tag is _ERR:
                            raise payload
                        else:
                            t0 = time.perf_counter()
                            pending.append(_to_device(payload))
                            device_put_ms.observe(
                                1e3 * (time.perf_counter() - t0))
                            prefetched.inc()
                    if not pending:
                        return
                    yield pending.popleft()
            finally:
                stop.set()
                while True:
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        break

        return _gen()


def get_worker_info():
    return None
