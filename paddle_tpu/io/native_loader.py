"""ctypes bridge to the C++ worker-pool dataloader (native/dataloader.cpp).

Python builds a producer callback (collate into a flat byte buffer); C++
threads run it concurrently and keep an ordered ring of ready batches. For
pure-C++ producers (pt_lm_window_producer) the whole pipeline runs without
the GIL. Auto-builds the .so with make on first use.
"""
import ctypes
import os
import pickle
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), '..', '..', 'native')
_LIB_PATH = os.path.join(_NATIVE_DIR, 'libpaddle_tpu_native.so')
_lib = None
_lib_lock = threading.Lock()

_PRODUCE_FN = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64,
                               ctypes.POINTER(ctypes.c_uint8),
                               ctypes.c_int64, ctypes.c_void_p)


def get_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            # build-once-under-lock is intentional: concurrent callers must
            # block until the shared library exists, and no device work can
            # be in flight before the first loader is constructed
            # pt-lint: disable=lock-blocking-call
            subprocess.run(['make', '-C', _NATIVE_DIR], check=True,
                           capture_output=True)
        lib = ctypes.CDLL(_LIB_PATH)
        lib.pt_pool_create.restype = ctypes.c_void_p
        lib.pt_pool_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                       ctypes.c_int64, _PRODUCE_FN,
                                       ctypes.c_void_p]
        lib.pt_pool_submit.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pt_pool_next.restype = ctypes.c_int64
        lib.pt_pool_next.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8)]
        lib.pt_pool_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class WorkerPool:
    """Generic pool: producer(index) -> bytes (pickled batch)."""

    def __init__(self, produce_py, n_workers=2, ring_cap=4,
                 batch_bytes=32 << 20):
        lib = get_lib()
        self.batch_bytes = batch_bytes

        def produce(index, dest, capacity, ctx):
            try:
                payload = produce_py(index)
                n = len(payload)
                if n > capacity:
                    return -1
                ctypes.memmove(dest, payload, n)
                return n
            except Exception:
                return -1

        self._cb = _PRODUCE_FN(produce)          # keep alive
        self._pool = lib.pt_pool_create(n_workers, ring_cap, batch_bytes,
                                        self._cb, None)
        self._buf = (ctypes.c_uint8 * batch_bytes)()
        self._lib = lib
        self._closed = False

    def submit(self, index):
        self._lib.pt_pool_submit(self._pool, index)

    def next(self):
        n = self._lib.pt_pool_next(self._pool, self._buf)
        if n < 0:
            return None
        return bytes(self._buf[:n])

    def close(self):
        if not self._closed:
            self._lib.pt_pool_destroy(self._pool)
            self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeWorkerIterator:
    """DataLoader iterator backed by the C++ pool: collation runs on worker
    threads, Python just unpickles ready batches in order."""

    def __init__(self, loader):
        self.loader = loader
        if loader.batch_sampler is None:
            raise RuntimeError('native loader needs a batch_sampler dataset')
        self.batches = list(loader.batch_sampler)
        dataset = loader.dataset
        collate = loader.collate_fn
        batches = self.batches

        def produce(i):
            items = [dataset[j] for j in batches[i]]
            out = collate(items)
            return pickle.dumps(_to_numpy(out), protocol=4)

        self.pool = WorkerPool(produce, n_workers=max(loader.num_workers, 1),
                               ring_cap=loader.prefetch_factor *
                               max(loader.num_workers, 1))
        self.n = len(self.batches)
        self.submitted = 0
        self.consumed = 0
        prefill = min(2 * max(loader.num_workers, 1), self.n)
        for _ in range(prefill):
            self.pool.submit(self.submitted)
            self.submitted += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self.consumed >= self.n:
            self.pool.close()
            raise StopIteration
        if self.submitted < self.n:
            self.pool.submit(self.submitted)
            self.submitted += 1
        payload = self.pool.next()
        self.consumed += 1
        if not payload:
            self.pool.close()
            raise StopIteration
        return _from_numpy(pickle.loads(payload))


def _to_numpy(obj):
    from ..core.tensor import Tensor
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy(v) for k, v in obj.items()}
    return obj


def _from_numpy(obj):
    from ..core.tensor import Tensor
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _from_numpy(v) for k, v in obj.items()}
    return obj


class LMTokenLoader:
    """Pure-C++ LM batcher: windows over a flat int32 token stream (no GIL)."""

    def __init__(self, tokens, batch_size, seq_len, stride=None, n_workers=2,
                 ring_cap=4):
        lib = get_lib()
        self.tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        self.batch_size = batch_size
        self.seq_len = seq_len
        stride = stride or seq_len

        class LmCtx(ctypes.Structure):
            _fields_ = [('stream', ctypes.c_void_p),
                        ('n_tokens', ctypes.c_int64),
                        ('seq_len', ctypes.c_int64),
                        ('stride', ctypes.c_int64),
                        ('batch', ctypes.c_int64)]

        self._ctx = LmCtx(self.tokens.ctypes.data, len(self.tokens),
                          seq_len, stride, batch_size)
        producer = ctypes.cast(lib.pt_lm_window_producer, _PRODUCE_FN)
        nbytes = batch_size * seq_len * 4
        self._pool = lib.pt_pool_create(n_workers, ring_cap, nbytes, producer,
                                        ctypes.byref(self._ctx))
        self._buf = (ctypes.c_uint8 * nbytes)()
        self._lib = lib
        self._nbytes = nbytes
        self._next_submit = 0
        for _ in range(ring_cap):
            self._lib.pt_pool_submit(self._pool, self._next_submit)
            self._next_submit += 1

    def next_batch(self):
        self._lib.pt_pool_submit(self._pool, self._next_submit)
        self._next_submit += 1
        n = self._lib.pt_pool_next(self._pool, self._buf)
        assert n == self._nbytes
        arr = np.frombuffer(bytes(self._buf[:n]), np.int32).reshape(
            self.batch_size, self.seq_len)
        return arr

    def close(self):
        self._lib.pt_pool_destroy(self._pool)
