"""Remaining top-level API surface (parity audit closers).
Reference: python/paddle/__init__.py exports not covered elsewhere.
"""
import jax
import jax.numpy as jnp
import numpy as np

from .core.dispatch import op, apply_op
from .core.tensor import Tensor
from .core import dtype as _dtype_mod

# type aliases
dtype = np.dtype
VarBase = Tensor

_default_dtype = 'float32'


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = str(np.dtype(_dtype_mod.convert_dtype(d)))


def get_default_dtype():
    return _default_dtype


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        # a NEW tensor, never an alias (in-place ops on the result must not
        # corrupt the input — same invariant as Tensor.t)
        return apply_op(lambda x: x + 0, inputs)
    return apply_op(lambda xs: sum(jnp.asarray(x) for x in xs), list(inputs))


@op
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def t(input, name=None):
    """Matrix transpose (reference tensor/linalg.py t): 0/1-D returns a
    copy, 2-D transposes, >2-D raises — the reference errors there too."""
    if input.ndim > 2:
        raise ValueError(
            'paddle.t only supports tensors with <= 2 dimensions; got '
            f'{input.ndim}-D (use paddle.transpose)')
    return _t_op(input)


@op
def _t_op(input):
    return input.T


def unstack(x, axis=0, num=None):
    from .tensor.manipulation import unbind
    return unbind(x, axis)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype='int64', name=None):
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.ones(arr.shape[0], bool)
    keep[1:] = np.any(arr[1:] != arr[:-1], axis=tuple(range(1, arr.ndim))) \
        if arr.ndim > 1 else arr[1:] != arr[:-1]
    vals = arr[keep]
    out = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(jnp.asarray(inv.astype('int64'))))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, arr.shape[0]))
        out.append(Tensor(jnp.asarray(counts.astype('int64'))))
    return out[0] if len(out) == 1 else tuple(out)


def crop_tensor(x, shape=None, offsets=None, name=None):
    from .tensor.manipulation import crop
    return crop(x, shape, offsets)


def scatter_(x, index, updates, overwrite=True, name=None):
    from .tensor.manipulation import scatter
    out = scatter(x, index, updates, overwrite)
    x._replace_value(out._value)
    return x


def tanh_(x, name=None):
    from .tensor.math import tanh
    out = tanh(x)
    x._replace_value(out._value)
    return x


def create_parameter(shape, dtype='float32', name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from .nn.layer_base import Parameter
    from .nn import initializer as I
    init = default_initializer or (I.Constant(0.0) if is_bias else I.XavierNormal())
    return Parameter(init(tuple(shape), _dtype_mod.convert_dtype(dtype)),
                     name=name)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw['precision'] = precision
    if threshold is not None:
        kw['threshold'] = threshold
    if edgeitems is not None:
        kw['edgeitems'] = edgeitems
    if linewidth is not None:
        kw['linewidth'] = linewidth
    if sci_mode is not None:
        kw['suppress'] = not sci_mode
    np.set_printoptions(**kw)


def set_grad_enabled(mode):
    from .autograd import set_grad_enabled as _s
    return _s(mode)


# dygraph-mode toggles (paddle 2.x dygraph == our eager mode)
def enable_dygraph(place=None):
    from .utils.misc import disable_static
    disable_static()


def disable_dygraph():
    from .utils.misc import enable_static
    enable_static()


def in_dygraph_mode():
    from .utils.misc import in_dynamic_mode
    return in_dynamic_mode()


def disable_signal_handler():
    pass


# flags / platform probes
_flags = {}


def set_flags(flags):
    _flags.update(flags)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {f: _flags.get(f) for f in flags}


def get_cudnn_version():
    return None


def get_cuda_rng_state():
    from .tensor.random import get_rng_state
    return [get_rng_state()]


def set_cuda_rng_state(state):
    from .tensor.random import set_rng_state
    if isinstance(state, (list, tuple)) and state:
        set_rng_state(state[0])


def monkey_patch_variable():
    pass


def monkey_patch_math_varbase():
    pass


def check_shape(shape):
    return True
