"""Einsum. Reference: python/paddle/tensor/einsum.py — here a direct jnp.einsum
lowering (XLA maps contractions onto the MXU)."""
import jax.numpy as jnp

from ..core.dispatch import apply_op


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply_op(lambda xs: jnp.einsum(equation, *xs), list(operands))
