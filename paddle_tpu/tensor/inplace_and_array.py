"""Module-level in-place op variants + TensorArray ops.
Reference: python/paddle/tensor/__init__.py (_ suffixed ops) and
python/paddle/tensor/array.py (LoDTensorArray ops used by static control flow).
"""
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import math as _math
from . import manipulation as _manip


def _inplace(base):
    def fn(x, *args, **kwargs):
        out = base(x, *args, **kwargs)
        x._replace_value(out._value)
        return x
    fn.__name__ = base.__name__ + '_'
    return fn


add_ = _inplace(_math.add)
subtract_ = _inplace(_math.subtract)
ceil_ = _inplace(_math.ceil)
floor_ = _inplace(_math.floor)
round_ = _inplace(_math.round)
exp_ = _inplace(_math.exp)
sqrt_ = _inplace(_math.sqrt)
rsqrt_ = _inplace(_math.rsqrt)
reciprocal_ = _inplace(_math.reciprocal)
clip_ = _inplace(_math.clip)
scale_ = _inplace(_math.scale)
flatten_ = _inplace(_manip.flatten)


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    from .random import uniform
    out = uniform(x.shape, x.dtype, min=min, max=max)
    x._replace_value(out._value)
    return x


# ---- TensorArray (list of tensors; static control-flow storage) ----------

def create_array(dtype='float32', initialized_list=None):
    arr = list(initialized_list) if initialized_list else []
    return arr


def array_write(x, i, array=None):
    idx = int(i.item() if isinstance(i, Tensor) else i)
    if array is None:
        array = []
    while len(array) <= idx:
        array.append(None)
    array[idx] = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    return array


def array_read(array, i):
    idx = int(i.item() if isinstance(i, Tensor) else i)
    return array[idx]


def array_length(array):
    return Tensor(jnp.asarray(len(array), jnp.int32))
