"""Tensor op library — aggregated namespace (paddle.tensor parity)."""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .attribute import shape, rank, is_complex, is_floating_point, is_integer  # noqa: F401
from .einsum import einsum  # noqa: F401
from .random import (  # noqa: F401
    bernoulli, multinomial, normal, poisson, rand, randint, randint_like,
    randn, randperm, seed, standard_normal, uniform)
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from .inplace_and_array import (  # noqa: F401
    add_, array_length, array_read, array_write, ceil_, clip_, create_array,
    exp_, flatten_, floor_, reciprocal_, round_, rsqrt_, scale_, sqrt_,
    subtract_, uniform_)
from .register import install as _install

_install()

# symbols the reference exports from paddle.tensor that live in compat_api
# here (compat_api only depends on core, so no import cycle)
from ..compat_api import (  # noqa: F401,E402
    add_n, diagonal, scatter_, set_printoptions, t, tanh_,
    unique_consecutive, unstack)
