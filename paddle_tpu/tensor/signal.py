"""Signal ops: frame, overlap_add, stft, istft.
Reference: python/paddle/tensor/signal.py."""
import jax
import jax.numpy as jnp

from ..core.dispatch import op
from ..core.tensor import Tensor


@op
def frame(x, frame_length, hop_length, axis=-1, name=None):
    n = x.shape[axis]
    n_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    moved = jnp.moveaxis(x, axis, -1)
    frames = moved[..., idx]                      # [..., n_frames, frame_length]
    if axis in (-1, x.ndim - 1):
        return jnp.moveaxis(frames, (-2, -1), (-1, -2))
    return frames


@op
def overlap_add(x, hop_length, axis=-1, name=None):
    # x: [..., frame_length, n_frames] (axis=-1 layout)
    moved = jnp.moveaxis(x, axis, -1) if axis not in (-1, x.ndim - 1) else x
    frame_length, n_frames = moved.shape[-2], moved.shape[-1]
    out_len = frame_length + hop_length * (n_frames - 1)
    base = jnp.zeros(moved.shape[:-2] + (out_len,), moved.dtype)

    def body(i, acc):
        return jax.lax.dynamic_update_slice_in_dim(
            acc, jax.lax.dynamic_slice_in_dim(acc, i * hop_length, frame_length,
                                              axis=-1) + moved[..., i],
            i * hop_length, axis=-1)
    return jax.lax.fori_loop(0, n_frames, body, base)


def _window_arr(window, n_fft, dtype):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    if isinstance(window, Tensor):
        return window._value.astype(dtype)
    return jnp.asarray(window).astype(dtype)


@op
def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode='reflect', normalized=False, onesided=True, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = _window_arr(window, win_length, jnp.float32)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad, n_fft - win_length - pad))
    sig = x
    if center:
        pads = [(0, 0)] * (sig.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        sig = jnp.pad(sig, pads, mode=pad_mode)
    n = sig.shape[-1]
    n_frames = 1 + (n - n_fft) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[:, None] + jnp.arange(n_fft)[None, :]
    frames = sig[..., idx] * w                      # [..., n_frames, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1) if onesided else \
        jnp.fft.fft(frames, axis=-1)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    return jnp.swapaxes(spec, -1, -2)               # [..., freq, n_frames]


@op
def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = _window_arr(window, win_length, jnp.float32)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad, n_fft - win_length - pad))
    spec = jnp.swapaxes(x, -1, -2)                  # [..., n_frames, freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided else \
        jnp.real(jnp.fft.ifft(spec, axis=-1))
    frames = frames * w
    n_frames = frames.shape[-2]
    out_len = n_fft + hop_length * (n_frames - 1)
    sig = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
    win_sq = jnp.zeros((out_len,), frames.dtype)

    def body(i, carry):
        s, ws = carry
        seg = jax.lax.dynamic_slice_in_dim(s, i * hop_length, n_fft, axis=-1)
        s = jax.lax.dynamic_update_slice_in_dim(s, seg + frames[..., i, :],
                                                i * hop_length, axis=-1)
        wseg = jax.lax.dynamic_slice_in_dim(ws, i * hop_length, n_fft, axis=-1)
        ws = jax.lax.dynamic_update_slice_in_dim(ws, wseg + jnp.square(w),
                                                 i * hop_length, axis=-1)
        return s, ws

    sig, win_sq = jax.lax.fori_loop(0, n_frames, body, (sig, win_sq))
    sig = sig / jnp.maximum(win_sq, 1e-10)
    if center:
        sig = sig[..., n_fft // 2:-(n_fft // 2)]
    if length is not None:
        sig = sig[..., :length]
    return sig
