"""Search/sort ops. Reference: python/paddle/tensor/search.py."""
import jax
import jax.numpy as jnp

from ..core.dispatch import op, apply_op
from ..core.tensor import Tensor


@op
def argmax(x, axis=None, keepdim=False, dtype='int64', name=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    out = jnp.argmax(x, axis=axis).astype(jnp.int32)
    return jnp.expand_dims(out, axis) if keepdim else out


@op
def argmin(x, axis=None, keepdim=False, dtype='int64', name=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    out = jnp.argmin(x, axis=axis).astype(jnp.int32)
    return jnp.expand_dims(out, axis) if keepdim else out


@op
def argsort(x, axis=-1, descending=False, name=None):
    out = jnp.argsort(-x if descending else x, axis=axis)
    return out.astype(jnp.int32)


@op
def sort(x, axis=-1, descending=False, name=None):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    """Returns (values, indices); indices computed outside the tape so only
    values carry gradient (gather via take_along_axis keeps the vjp)."""
    if isinstance(k, Tensor):
        k = int(k.item())
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    ax = axis if axis >= 0 else v.ndim + axis
    moved = jnp.moveaxis(v, ax, -1)
    _, idx = jax.lax.top_k(moved if largest else -moved, k)
    idx = jnp.moveaxis(idx, -1, ax)
    from .manipulation import take_along_axis
    idx_t = Tensor(idx.astype(jnp.int32))
    vals = take_along_axis(x, idx_t, axis=ax) if isinstance(x, Tensor) else \
        Tensor(jnp.take_along_axis(v, idx, axis=ax))
    return vals, idx_t


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    vals, idx = topk(x, k, axis=axis, largest=False)
    from .manipulation import take_along_axis
    from .creation import full
    ax = axis if axis >= 0 else x.ndim + axis
    sel = take_along_axis(vals, Tensor(jnp.full([1 if i == ax else s for i, s in enumerate(vals.shape)],
                                                k - 1, jnp.int32)), axis=ax)
    sel_idx = take_along_axis(idx, Tensor(jnp.full([1 if i == ax else s for i, s in enumerate(idx.shape)],
                                                   k - 1, jnp.int32)), axis=ax)
    if not keepdim:
        from .manipulation import squeeze
        sel, sel_idx = squeeze(sel, ax), squeeze(sel_idx, ax)
    return sel, sel_idx


@op
def where(condition, x=None, y=None, name=None):
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    import numpy as np
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(v[:, None].astype('int64'))) for v in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype('int64')))


@op
def masked_select_dense(x, mask):
    return jnp.where(mask, x, 0)


def masked_select(x, mask, name=None):
    import numpy as np
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    m = np.asarray(mask._value if isinstance(mask, Tensor) else mask)
    return Tensor(jnp.asarray(arr[np.broadcast_to(m, arr.shape)]))


@op
def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = 'right' if right else 'left'
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            jnp.reshape(sorted_sequence, (-1, sorted_sequence.shape[-1])),
            jnp.reshape(values, (-1, values.shape[-1])))
        out = jnp.reshape(out, values.shape)
    # out_int32 kept for API parity but moot: x64 is off, so the int64
    # branch would canonicalize to int32 anyway.
    del out_int32
    return out.astype(jnp.int32)


def index_put(x, indices, value, accumulate=False):
    def pure(v, val):
        idx = tuple(jnp.asarray(i._value if isinstance(i, Tensor) else i) for i in indices)
        return v.at[idx].add(val) if accumulate else v.at[idx].set(val)
    return apply_op(pure, x, value)
