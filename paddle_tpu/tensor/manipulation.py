"""Shape/layout manipulation ops. Reference: python/paddle/tensor/manipulation.py."""
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import op, apply_op
from ..core.tensor import Tensor


def _static_shape(shape):
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            out.append(int(np.asarray(s._value)))
        else:
            from jax import export as _jax_export
            if _jax_export.is_symbolic_dim(s):
                # jax.export shape polymorphism — pass through unresolved
                out.append(s)
            else:
                out.append(int(s))
    return tuple(out)


@op
def reshape(x, shape, name=None):
    return jnp.reshape(x, _static_shape(shape))


reshape_ = reshape


@op
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    if stop_axis < 0:
        stop_axis += nd
    if start_axis < 0:
        start_axis += nd
    shape = list(x.shape)
    mid = 1
    for s in shape[start_axis:stop_axis + 1]:
        mid *= s
    return jnp.reshape(x, tuple(shape[:start_axis]) + (mid,) + tuple(shape[stop_axis + 1:]))


@op
def transpose(x, perm, name=None):
    return jnp.transpose(x, axes=tuple(perm))


@op
def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


@op
def swapaxes(x, axis1, axis2, name=None):
    return jnp.swapaxes(x, axis1, axis2)


@op
def squeeze(x, axis=None, name=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        return jnp.squeeze(x, axis=axis) if axis else x
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


squeeze_ = squeeze


@op
def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        for a in sorted(axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, axis)


unsqueeze_ = unsqueeze


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda xs: jnp.concatenate([jnp.asarray(v) for v in xs], axis=axis), list(x))


def stack(x, axis=0, name=None):
    return apply_op(lambda xs: jnp.stack([jnp.asarray(v) for v in xs], axis=axis), list(x))


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis] if isinstance(x, Tensor) else x.shape[axis]

    if isinstance(num_or_sections, int):
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s) for s in num_or_sections]
        n_unknown = builtins_sum(1 for s in sections if s < 0)
        if n_unknown:
            known = builtins_sum(s for s in sections if s >= 0)
            sections = [s if s >= 0 else dim - known for s in sections]
    offsets = np.cumsum([0] + sections).tolist()

    def pure(v):
        return [jnp.take(v, jnp.arange(offsets[i], offsets[i + 1]), axis=axis)
                for i in range(len(sections))]
    return apply_op(pure, x)


def builtins_sum(it):
    import builtins
    return builtins.sum(it)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(input, axis=0, name=None):
    n = input.shape[axis]
    return apply_op(
        lambda v: [jnp.squeeze(jnp.take(v, jnp.array([i]), axis=axis), axis=axis)
                   for i in range(n)], input)


@op
def tile(x, repeat_times, name=None):
    return jnp.tile(x, _static_shape(repeat_times))


@op
def expand(x, shape, name=None):
    shape = _static_shape(shape)
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s in (-1,) else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@op
def expand_as(x, y, name=None):
    return jnp.broadcast_to(x, y.shape)


@op
def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, _static_shape(shape))


def broadcast_tensors(input, name=None):
    return apply_op(lambda xs: list(jnp.broadcast_arrays(*xs)), list(input))


@op
def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(axis))


def reverse(x, axis, name=None):
    return flip(x, axis)


@op
def roll(x, shifts, axis=None, name=None):
    return jnp.roll(x, shifts, axis=axis)


@op
def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@op
def gather(x, index, axis=0, name=None):
    idx = jnp.reshape(jnp.asarray(index), (-1,))
    if isinstance(axis, (Tensor,)):
        axis = int(axis.item())
    return jnp.take(x, idx.astype(jnp.int32), axis=axis)


@op
def gather_nd(x, index, name=None):
    index = jnp.asarray(index).astype(jnp.int32)
    return x[tuple(jnp.moveaxis(index, -1, 0))]


@op
def scatter(x, index, updates, overwrite=True, name=None):
    index = jnp.reshape(jnp.asarray(index), (-1,)).astype(jnp.int32)
    if overwrite:
        return x.at[index].set(updates)
    base = x.at[index].set(jnp.zeros_like(jnp.asarray(updates)))
    return base.at[index].add(updates)


@op
def scatter_nd_add(x, index, updates, name=None):
    index = jnp.asarray(index).astype(jnp.int32)
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    base = zeros(shape, dtype=updates.dtype if isinstance(updates, Tensor) else 'float32')
    return scatter_nd_add(base, index, updates)


@op
def put_along_axis(arr, indices, values, axis, reduce='assign'):
    indices = jnp.asarray(indices).astype(jnp.int32)
    if reduce == 'add':
        f = lambda a, i, v: a.at[i].add(v)
    elif reduce == 'multiply':
        f = lambda a, i, v: a.at[i].multiply(v)
    else:
        f = lambda a, i, v: a.at[i].set(v)
    idx = []
    for d in range(arr.ndim):
        if d == axis:
            idx.append(indices)
        else:
            sh = [1] * arr.ndim
            sh[d] = arr.shape[d]
            idx.append(jnp.reshape(jnp.arange(arr.shape[d]), sh))
    return f(arr, tuple(jnp.broadcast_arrays(*idx)), values)


@op
def take_along_axis(arr, indices, axis):
    return jnp.take_along_axis(arr, jnp.asarray(indices).astype(jnp.int32), axis=axis)


@op
def index_select(x, index, axis=0, name=None):
    return jnp.take(x, jnp.reshape(jnp.asarray(index), (-1,)).astype(jnp.int32), axis=axis)


@op
def index_sample(x, index):
    index = jnp.asarray(index).astype(jnp.int32)
    return jnp.take_along_axis(x, index, axis=1)


@op
def slice(input, axes, starts, ends):
    idx = [jnp.arange(0, s) for s in input.shape]
    sl = [None] * input.ndim
    for ax, st, en in zip(axes, starts, ends):
        dim = input.shape[ax]
        st = int(st) if not isinstance(st, Tensor) else int(st.item())
        en = int(en) if not isinstance(en, Tensor) else int(en.item())
        if st < 0:
            st += dim
        if en < 0:
            en += dim
        en = builtins_min(en, dim)
        sl[ax] = (st, en)
    slicer = tuple(jnp.s_[s[0]:s[1]] if s is not None else jnp.s_[:] for s in sl)
    return input[slicer]


def builtins_min(a, b):
    import builtins
    return builtins.min(a, b)


@op
def strided_slice(x, axes, starts, ends, strides, name=None):
    slicer = [jnp.s_[:]] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        slicer[ax] = jnp.s_[st:en:sd]
    return x[tuple(slicer)]


@op
def unique_consecutive_vals(x):
    return x


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype='int64', name=None):
    vals = np.unique(np.asarray(x._value if isinstance(x, Tensor) else x),
                     return_index=return_index, return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if not isinstance(vals, tuple):
        return Tensor(jnp.asarray(vals))
    return tuple(Tensor(jnp.asarray(v)) for v in vals)


@op
def repeat_interleave(x, repeats, axis=None, name=None):
    return jnp.repeat(x, repeats, axis=axis)


@op
def as_complex(x, name=None):
    return jax.lax.complex(x[..., 0], x[..., 1]) if False else x[..., 0] + 1j * x[..., 1]


@op
def as_real(x, name=None):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@op
def real(x, name=None):
    return jnp.real(x)


@op
def imag(x, name=None):
    return jnp.imag(x)


@op
def cast(x, dtype):
    from ..core.dtype import convert_dtype
    return x.astype(convert_dtype(dtype))


@op
def crop(x, shape=None, offsets=None, name=None):
    shape = _static_shape(shape)
    offsets = _static_shape(offsets) if offsets is not None else (0,) * len(shape)
    slicer = tuple(jnp.s_[o:o + s] for o, s in zip(offsets, shape))
    return x[slicer]


@op
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    mask = (input // size) == shard_id
    return jnp.where(mask, input % size, ignore_value)


def tensordot(x, y, axes=2, name=None):
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


import jax  # noqa: E402  (used by as_complex)
