"""Linear algebra ops. Reference: python/paddle/tensor/linalg.py."""
import jax.numpy as jnp

from ..core.dispatch import op


@op
def transpose_last2(x):
    return jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x


@op
def norm(x, p='fro', axis=None, keepdim=False, name=None):
    if p == 'fro':
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
                                keepdims=keepdim))
    if p == float('inf'):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float('-inf'):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=ax, keepdims=keepdim), 1.0 / p)


@op
def dist(x, y, p=2, name=None):
    d = jnp.abs(x - y)
    if p == 0:
        return jnp.sum((d != 0).astype(x.dtype)).astype(x.dtype)
    if p == float('inf'):
        return jnp.max(d)
    if p == float('-inf'):
        return jnp.min(d)
    return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)


@op
def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


@op
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@op
def cholesky_solve(x, y, upper=False, name=None):
    import jax.scipy.linalg as jsl
    return jsl.cho_solve((y, not upper), x)


@op
def inverse(x, name=None):
    return jnp.linalg.inv(x)


@op
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@op
def det(x, name=None):
    return jnp.linalg.det(x)


@op
def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@op
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    from ..core.dispatch import apply_op
    return apply_op(lambda v: jnp.linalg.matrix_rank(v, tol), x)


@op
def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2).conj()


@op
def qr(x, mode='reduced', name=None):
    return tuple(jnp.linalg.qr(x, mode=mode)) if mode != 'r' else jnp.linalg.qr(x, mode='r')


@op
def eig(x, name=None):
    return tuple(jnp.linalg.eig(x))


@op
def eigh(x, UPLO='L', name=None):
    return tuple(jnp.linalg.eigh(x, UPLO=UPLO))


@op
def eigvals(x, name=None):
    return jnp.linalg.eigvals(x)


@op
def eigvalsh(x, UPLO='L', name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@op
def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@op
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@op
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    import jax.scipy.linalg as jsl
    return jsl.solve_triangular(x, y, lower=not upper, trans=1 if transpose else 0,
                                unit_diagonal=unitriangular)


@op
def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return jnp.cross(x, y, axis=axis)


@op
def bmm(x, y, name=None):
    return jnp.einsum('bij,bjk->bik', x, y)


@op
def histogram(input, bins=100, min=0, max=0, name=None):
    if min == 0 and max == 0:
        lo, hi = jnp.min(input), jnp.max(input)
    else:
        lo, hi = min, max
    return jnp.histogram(input, bins=bins, range=(lo, hi))[0].astype(jnp.int32)


@op
def bincount(x, weights=None, minlength=0, name=None):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)


@op
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@op
def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(x)


@op
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@op
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


# reference paddle.linalg exports 'inv' as the canonical name
inv = inverse
