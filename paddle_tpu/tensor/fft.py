"""FFT ops. Reference: python/paddle/tensor/fft.py."""
import jax.numpy as jnp

from ..core.dispatch import op


@op
def fft(x, n=None, axis=-1, norm='backward', name=None):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


@op
def ifft(x, n=None, axis=-1, norm='backward', name=None):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


@op
def rfft(x, n=None, axis=-1, norm='backward', name=None):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


@op
def irfft(x, n=None, axis=-1, norm='backward', name=None):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


@op
def fft2(x, s=None, axes=(-2, -1), norm='backward', name=None):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=norm)


@op
def ifft2(x, s=None, axes=(-2, -1), norm='backward', name=None):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=norm)


@op
def fftn(x, s=None, axes=None, norm='backward', name=None):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


@op
def ifftn(x, s=None, axes=None, norm='backward', name=None):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


@op
def rfft2(x, s=None, axes=(-2, -1), norm='backward', name=None):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=norm)


@op
def irfft2(x, s=None, axes=(-2, -1), norm='backward', name=None):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=norm)


@op
def hfft(x, n=None, axis=-1, norm='backward', name=None):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


@op
def ihfft(x, n=None, axis=-1, norm='backward', name=None):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


@op
def fftshift(x, axes=None, name=None):
    return jnp.fft.fftshift(x, axes=axes)


@op
def ifftshift(x, axes=None, name=None):
    return jnp.fft.ifftshift(x, axes=axes)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from ..core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))
