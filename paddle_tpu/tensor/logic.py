"""Logic/comparison ops. Reference: python/paddle/tensor/logic.py."""
import jax.numpy as jnp

from ..core.dispatch import op


@op
def equal(x, y, name=None):
    return jnp.equal(jnp.asarray(x), jnp.asarray(y))


@op
def not_equal(x, y, name=None):
    return jnp.not_equal(jnp.asarray(x), jnp.asarray(y))


@op
def greater_than(x, y, name=None):
    return jnp.greater(jnp.asarray(x), jnp.asarray(y))


@op
def greater_equal(x, y, name=None):
    return jnp.greater_equal(jnp.asarray(x), jnp.asarray(y))


@op
def less_than(x, y, name=None):
    return jnp.less(jnp.asarray(x), jnp.asarray(y))


@op
def less_equal(x, y, name=None):
    return jnp.less_equal(jnp.asarray(x), jnp.asarray(y))


@op
def logical_and(x, y, out=None, name=None):
    return jnp.logical_and(x, y)


@op
def logical_or(x, y, out=None, name=None):
    return jnp.logical_or(x, y)


@op
def logical_xor(x, y, out=None, name=None):
    return jnp.logical_xor(x, y)


@op
def logical_not(x, out=None, name=None):
    return jnp.logical_not(x)


@op
def equal_all(x, y, name=None):
    return jnp.all(jnp.equal(x, y))


@op
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@op
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@op
def is_empty(x, name=None):
    return jnp.asarray(x.size == 0)


def is_tensor(x):
    from ..core.tensor import Tensor
    return isinstance(x, Tensor)
