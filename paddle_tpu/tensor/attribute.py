"""Tensor attribute helpers. Reference: python/paddle/tensor/attribute.py."""
import jax.numpy as jnp

from ..core.tensor import Tensor


def shape(x):
    return Tensor(jnp.asarray(x.shape, jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, jnp.int32))


def is_complex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x.dtype, jnp.integer)
