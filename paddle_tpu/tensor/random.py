"""Random ops with a global, explicitly-splittable PRNG.

Reference: python/paddle/tensor/random.py + fluid Generator. TPU-native twist:
a single global JAX PRNG key, split per call; ``paddle_tpu.seed(n)`` resets it.
Inside jitted/functional code, prefer passing keys explicitly (utils.rng).
"""
import threading

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes

_lock = threading.Lock()
_KEY = None   # lazy: creating a key initializes the JAX backend; defer until
              # first use so `import paddle_tpu` never touches the device.


def seed(s):
    global _KEY
    with _lock:
        _KEY = jax.random.PRNGKey(int(s))
    return _KEY


_ctx = threading.local()


class rng_scope:
    """Derive keys from an explicit (possibly traced) base key instead of the
    global generator — makes stochastic layers (dropout) correct under jit:
    the base key is a traced argument, so each step gets fresh randomness
    without retracing."""

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        if not hasattr(_ctx, 'stack'):
            _ctx.stack = []
        _ctx.stack.append([self.key, 0])
        return self

    def __exit__(self, *exc):
        _ctx.stack.pop()
        return False


def next_key():
    """Fresh subkey: from the innermost rng_scope if active (trace-safe),
    else by splitting the global key (thread-safe)."""
    stack = getattr(_ctx, 'stack', None)
    if stack:
        entry = stack[-1]
        entry[1] += 1
        return jax.random.fold_in(entry[0], entry[1])
    global _KEY
    with _lock:
        if _KEY is None:
            _KEY = jax.random.PRNGKey(0)
        _KEY, sub = jax.random.split(_KEY)
    return sub


def _ensure_key():
    global _KEY
    with _lock:
        if _KEY is None:
            _KEY = jax.random.PRNGKey(0)
        return _KEY


def get_rng_state():
    return _ensure_key()


def set_rng_state(state):
    global _KEY
    _KEY = state


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item() if isinstance(s, Tensor) else s) for s in shape)


def _dt(dtype, default='float32'):
    return dtypes.convert_dtype(dtype if dtype is not None else default)


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), _shape(shape), _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(m + s * jax.random.normal(next_key(), sh))
    return Tensor(mean + std * jax.random.normal(next_key(), _shape(shape or [1])))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return Tensor(jax.random.uniform(next_key(), _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high,
                                     _dt(dtype, 'int64')))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype='int64', name=None):
    return Tensor(jax.random.permutation(next_key(), n).astype(_dt(dtype, 'int64')))


def multinomial(x, num_samples=1, replacement=False, name=None):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(num_samples,) + v.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(next_key(), v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int32))


def bernoulli(x, name=None):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.bernoulli(next_key(), v).astype(v.dtype))


def poisson(x, name=None):
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(next_key(), v).astype(v.dtype))


def exponential_(x, lam=1.0, name=None):
    v = jax.random.exponential(next_key(), tuple(x.shape)) / lam
    x._replace_value(v.astype(x.dtype))
    return x
