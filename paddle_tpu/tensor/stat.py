"""Statistics ops. Reference: python/paddle/tensor/stat.py."""
import jax.numpy as jnp

from ..core.dispatch import op


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@op
def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=_axes(axis), keepdims=keepdim)


@op
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(x, axis=_axes(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@op
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(x, axis=_axes(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@op
def median(x, axis=None, keepdim=False, name=None):
    if axis is None:
        return jnp.median(jnp.reshape(x, (-1,)))
    return jnp.median(x, axis=axis, keepdims=keepdim)


@op
def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=_axes(axis), keepdims=keepdim)


@op
def quantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.quantile(x, jnp.asarray(q), axis=_axes(axis), keepdims=keepdim)


@op
def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return jnp.nanquantile(x, jnp.asarray(q), axis=_axes(axis), keepdims=keepdim)


@op
def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=_axes(axis), keepdims=keepdim)


@op
def numel(x, name=None):
    return jnp.asarray(x.size, jnp.int32)


@op
def mode(x, axis=-1, keepdim=False, name=None):
    # mode along axis via sorted-run trick (compile-friendly)
    sortd = jnp.sort(x, axis=axis)
    n = x.shape[axis]
    eq = jnp.equal(jnp.take(sortd, jnp.arange(1, n), axis=axis),
                   jnp.take(sortd, jnp.arange(0, n - 1), axis=axis))
    runlen = jnp.cumsum(eq.astype(jnp.int32), axis=axis)
    reset = jnp.where(eq, 0, 1)
    # fallback simple approach: pick value with max count via comparison matrix
    xm = jnp.moveaxis(x, axis, -1)
    counts = jnp.sum(xm[..., :, None] == xm[..., None, :], axis=-1)
    idx = jnp.argmax(counts, axis=-1)
    vals = jnp.take_along_axis(xm, idx[..., None], axis=-1)[..., 0]
    if keepdim:
        vals = jnp.expand_dims(jnp.moveaxis(vals, -1, -1), axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int32)
