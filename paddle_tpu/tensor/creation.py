"""Creation ops. Reference: python/paddle/tensor/creation.py."""
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import op, apply_op
from ..core.tensor import Tensor, to_tensor
from ..core import dtype as dtypes


def _dt(dtype, default='float32'):
    return dtypes.convert_dtype(dtype if dtype is not None else default)


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value if isinstance(s, Tensor) else s) for s in shape)


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


@op
def ones_like(x, dtype=None, name=None):
    return jnp.ones_like(x, dtype=dtypes.convert_dtype(dtype))


@op
def zeros_like(x, dtype=None, name=None):
    return jnp.zeros_like(x, dtype=dtypes.convert_dtype(dtype))


@op
def full_like(x, fill_value, dtype=None, name=None):
    return jnp.full_like(x, fill_value, dtype=dtypes.convert_dtype(dtype))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = 'int64' if all(isinstance(v, (int, np.integer)) for v in (start, end, step)) \
            else 'float32'
    return Tensor(jnp.arange(start, end, step, dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


@op
def diag(x, offset=0, padding_value=0, name=None):
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n), padding_value, x.dtype)
        idx = jnp.arange(x.shape[0])
        r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
        return base.at[r, c].set(x)
    return jnp.diag(x, k=offset)


@op
def diagflat(x, offset=0, name=None):
    return jnp.diagflat(x, k=offset)


@op
def tril(x, diagonal=0, name=None):
    return jnp.tril(x, k=diagonal)


@op
def triu(x, diagonal=0, name=None):
    return jnp.triu(x, k=diagonal)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return apply_op(lambda xs: list(jnp.meshgrid(*xs, indexing='ij')), list(args))


@op
def kron(x, y, name=None):
    return jnp.kron(x, y)


@op
def complex(real, imag, name=None):
    return jnp.asarray(real) + 1j * jnp.asarray(imag)


@op
def assign(x, output=None):
    return jnp.asarray(x)


def clone(x, name=None):
    return x.clone()


def tolist(x):
    return x.tolist()
