"""Math ops. Reference: python/paddle/tensor/math.py (~120 ops)."""
import sys

import jax
import jax.numpy as jnp

from ..core.dispatch import op, apply_op
from ..core.tensor import Tensor
from ..core import dtype as dtypes

_mod = sys.modules[__name__]

# ---- table-generated unary ops --------------------------------------------
_UNARY = {
    'abs': jnp.abs, 'acos': jnp.arccos, 'asin': jnp.arcsin, 'atan': jnp.arctan,
    'acosh': jnp.arccosh, 'asinh': jnp.arcsinh, 'atanh': jnp.arctanh,
    'ceil': jnp.ceil, 'cos': jnp.cos, 'cosh': jnp.cosh, 'exp': jnp.exp,
    'expm1': jnp.expm1, 'floor': jnp.floor, 'log': jnp.log, 'log2': jnp.log2,
    'log10': jnp.log10, 'log1p': jnp.log1p, 'neg': jnp.negative,
    'reciprocal': jnp.reciprocal, 'round': jnp.round, 'rsqrt': jax.lax.rsqrt,
    'sign': jnp.sign, 'sin': jnp.sin, 'sinh': jnp.sinh, 'sqrt': jnp.sqrt,
    'square': jnp.square, 'tan': jnp.tan, 'tanh': jnp.tanh,
    'erf': jax.scipy.special.erf, 'erfinv': jax.scipy.special.erfinv,
    'digamma': jax.scipy.special.digamma, 'lgamma': jax.scipy.special.gammaln,
    'angle': jnp.angle, 'conj': jnp.conj, 'trunc': jnp.trunc,
    'frac': lambda x: x - jnp.trunc(x),
}
for _name, _fn in _UNARY.items():
    def _make(fn):
        def _f(x, name=None):
            return fn(x)
        return _f
    setattr(_mod, _name, op(_make(_fn)))

# ---- table-generated binary ops -------------------------------------------
_BINARY = {
    'add': jnp.add, 'subtract': jnp.subtract, 'multiply': jnp.multiply,
    'divide': jnp.divide, 'floor_divide': jnp.floor_divide,
    'mod': jnp.mod, 'remainder': jnp.mod, 'floor_mod': jnp.mod,
    'pow': jnp.power, 'maximum': jnp.maximum, 'minimum': jnp.minimum,
    'fmax': jnp.fmax, 'fmin': jnp.fmin, 'atan2': jnp.arctan2,
    'logaddexp': jnp.logaddexp,
    'bitwise_and': jnp.bitwise_and, 'bitwise_or': jnp.bitwise_or,
    'bitwise_xor': jnp.bitwise_xor,
}
for _name, _fn in _BINARY.items():
    def _make2(fn):
        def _f(x, y, name=None):
            return fn(jnp.asarray(x), jnp.asarray(y))
        return _f
    setattr(_mod, _name, op(_make2(_fn)))


@op
def bitwise_not(x, name=None):
    return jnp.bitwise_not(x)


@op
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    if act == 'relu':
        out = jnp.maximum(out, 0)
    elif act == 'tanh':
        out = jnp.tanh(out)
    return out


@op
def clip(x, min=None, max=None, name=None):
    return jnp.clip(x, a_min=min, a_max=max)


@op
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


@op
def multiplex(inputs, index, name=None):
    stacked = jnp.stack(inputs, axis=0)           # [n, batch, ...]
    idx = jnp.reshape(jnp.asarray(index), (-1,)).astype(jnp.int32)
    return stacked[idx, jnp.arange(stacked.shape[1])]


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@op
def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.sum(x, axis=_axes(axis), dtype=dtypes.convert_dtype(dtype),
                   keepdims=keepdim)


@op
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.nansum(x, axis=_axes(axis), dtype=dtypes.convert_dtype(dtype),
                      keepdims=keepdim)


@op
def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return jnp.prod(x, axis=_axes(axis), dtype=dtypes.convert_dtype(dtype),
                    keepdims=keepdim)


@op
def max(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axes(axis), keepdims=keepdim)


@op
def min(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axes(axis), keepdims=keepdim)


@op
def amax(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axes(axis), keepdims=keepdim)


@op
def amin(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axes(axis), keepdims=keepdim)


@op
def logsumexp(x, axis=None, keepdim=False, name=None):
    return jax.scipy.special.logsumexp(x, axis=_axes(axis), keepdims=keepdim)


@op
def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtypes.convert_dtype(dtype))


@op
def cumprod(x, dim=None, dtype=None, name=None):
    return jnp.cumprod(x, axis=dim, dtype=dtypes.convert_dtype(dtype))


@op
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@op
def mm(x, y, name=None):
    return jnp.matmul(x, y)


@op
def inner(x, y, name=None):
    return jnp.inner(x, y)


@op
def outer(x, y, name=None):
    return jnp.outer(x, y)


@op
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@op
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * jnp.matmul(x, y)


@op
def multiply_(x, y):
    return jnp.multiply(x, y)


@op
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@op
def isfinite(x, name=None):
    return jnp.isfinite(x)


@op
def isinf(x, name=None):
    return jnp.isinf(x)


@op
def isnan(x, name=None):
    return jnp.isnan(x)


@op
def all(x, axis=None, keepdim=False, name=None):
    return jnp.all(x, axis=_axes(axis), keepdims=keepdim)


@op
def any(x, axis=None, keepdim=False, name=None):
    return jnp.any(x, axis=_axes(axis), keepdims=keepdim)


@op
def broadcast_shape_op(x, y):
    return jnp.broadcast_arrays(x, y)


def broadcast_shape(x_shape, y_shape):
    import numpy as np
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@op
def increment(x, value=1.0, name=None):
    return x + value


@op
def lerp(x, y, weight, name=None):
    return x + jnp.asarray(weight) * (y - x)


@op
def rad2deg(x, name=None):
    return jnp.rad2deg(x)


@op
def deg2rad(x, name=None):
    return jnp.deg2rad(x)


@op
def gcd(x, y, name=None):
    return jnp.gcd(x, y)


@op
def lcm(x, y, name=None):
    return jnp.lcm(x, y)


@op
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@op
def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


@op
def log_(x):
    return jnp.log(x)


@op
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


def divide_int_aware(x, y):
    return divide(x, y)
