"""Attach op library as Tensor methods + Python operators.

Reference analogue: python/paddle/fluid/dygraph/math_op_patch.py and
varbase_patch_methods.py (monkey-patching VarBase).
"""
import jax.numpy as jnp

from ..core.dispatch import apply_op, op
from ..core.tensor import Tensor
from . import creation, einsum as einsum_mod, linalg, logic, manipulation, math, random, search, stat


def _method(fn):
    return fn


_METHOD_SOURCES = [math, manipulation, logic, linalg, search, stat]

_EXCLUDE = {'shape', 'rank', 'op', 'apply_op', 'Tensor', 'sys', 'jax', 'jnp', 'np',
            'builtins_sum', 'builtins_min', 'builtins_bool', 'broadcast_shape'}

_FROM_CREATION = ['ones_like', 'zeros_like', 'full_like', 'diag', 'diagflat',
                  'tril', 'triu', 'tolist']


def install():
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith('_') or name in _EXCLUDE or name[0].isupper():
                continue
            fn = getattr(mod, name)
            if callable(fn) and not isinstance(fn, type) and not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    for name in _FROM_CREATION:
        if not hasattr(Tensor, name):
            setattr(Tensor, name, getattr(creation, name))

    # remaining reference tensor_method_func names defined outside the
    # scanned modules (r4 method-audit fill). compat_api only depends on
    # core, so importing it here is cycle-free; the tensor submodules are
    # already imported by this package.
    from .. import compat_api as _compat
    from . import attribute as _attr, inplace_and_array as _inplace
    _extra_sources = [_compat, _attr, _inplace, creation] + _METHOD_SOURCES
    for name in ('add_n', 'diagonal', 'scatter_', 'unique_consecutive',
                 'unstack', 'kron', 'rank', 'flatten_'):
        fn = next((getattr(m, name) for m in _extra_sources
                   if hasattr(m, name)), None)
        if fn is not None and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
        elif fn is None:
            raise AttributeError(f'tensor method {name!r} has no source')
    # broadcast_shape operates on SHAPES; the only sensible method form
    # uses self's shape as x_shape
    if not hasattr(Tensor, 'broadcast_shape'):
        Tensor.broadcast_shape = (
            lambda self, y_shape: math.broadcast_shape(self.shape, y_shape))

    # paddle method-only names
    Tensor.astype = lambda self, dtype: manipulation.cast(self, dtype)
    Tensor.cast = Tensor.astype

    def _t(self, name=None):
        # one shared implementation with paddle.t (always a NEW tensor —
        # aliasing self would let in-place ops on the result corrupt it)
        from ..compat_api import t as _t_fn
        return _t_fn(self)
    Tensor.t = _t
    Tensor.dim = lambda self: self.ndim
    Tensor.numel = lambda self: stat.numel(self)
    Tensor.einsum = None  # not a method
    del Tensor.einsum
    Tensor.uniform_ = _inplace_random(random.uniform)
    Tensor.normal_ = lambda self, mean=0.0, std=1.0: _set_inplace(
        self, random.normal(mean, std, self.shape))
    Tensor.zero_ = lambda self: _set_inplace(self, creation.zeros(self.shape, self.dtype))
    Tensor.fill_ = lambda self, v: _set_inplace(self, creation.full(self.shape, v, self.dtype))
    Tensor.exponential_ = random.exponential_

    # in-place arithmetic aliases (functional under the hood)
    for nm in ['add', 'subtract', 'multiply', 'divide', 'clip', 'scale', 'floor',
               'ceil', 'round', 'sqrt', 'rsqrt', 'reciprocal', 'exp', 'tanh']:
        base = getattr(math, nm, None) or getattr(manipulation, nm, None)
        if base is not None:
            setattr(Tensor, nm + '_', _make_inplace(base))

    # operators
    Tensor.__add__ = lambda s, o: math.add(s, _c(o))
    Tensor.__radd__ = lambda s, o: math.add(_c(o), s)
    Tensor.__sub__ = lambda s, o: math.subtract(s, _c(o))
    Tensor.__rsub__ = lambda s, o: math.subtract(_c(o), s)
    Tensor.__mul__ = lambda s, o: math.multiply(s, _c(o))
    Tensor.__rmul__ = lambda s, o: math.multiply(_c(o), s)
    Tensor.__truediv__ = lambda s, o: math.divide(s, _c(o))
    Tensor.__rtruediv__ = lambda s, o: math.divide(_c(o), s)
    Tensor.__floordiv__ = lambda s, o: math.floor_divide(s, _c(o))
    Tensor.__mod__ = lambda s, o: math.mod(s, _c(o))
    Tensor.__pow__ = lambda s, o: math.pow(s, _c(o))
    Tensor.__rpow__ = lambda s, o: math.pow(_c(o), s)
    Tensor.__neg__ = lambda s: math.neg(s)
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__matmul__ = lambda s, o: math.matmul(s, _c(o))
    Tensor.__rmatmul__ = lambda s, o: math.matmul(_c(o), s)
    Tensor.__eq__ = lambda s, o: logic.equal(s, _c(o))
    Tensor.__ne__ = lambda s, o: logic.not_equal(s, _c(o))
    Tensor.__lt__ = lambda s, o: logic.less_than(s, _c(o))
    Tensor.__le__ = lambda s, o: logic.less_equal(s, _c(o))
    Tensor.__gt__ = lambda s, o: logic.greater_than(s, _c(o))
    Tensor.__ge__ = lambda s, o: logic.greater_equal(s, _c(o))
    Tensor.__and__ = lambda s, o: math.bitwise_and(s, _c(o))
    Tensor.__or__ = lambda s, o: math.bitwise_or(s, _c(o))
    Tensor.__xor__ = lambda s, o: math.bitwise_xor(s, _c(o))
    Tensor.__invert__ = lambda s: math.bitwise_not(s)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem


def _c(o):
    return o


def _make_inplace(base):
    def f(self, *args, **kwargs):
        out = base(self, *args, **kwargs)
        return _set_inplace(self, out)
    return f


def _inplace_random(gen):
    def f(self, min=-1.0, max=1.0, seed=0):
        return _set_inplace(self, random.uniform(self.shape, self.dtype, min=min, max=max))
    return f


def _set_inplace(t, new):
    t._replace_value(new._value if isinstance(new, Tensor) else new)
    return t


def _norm_index(item):
    """Convert Tensor indices to jax arrays; pass through slices/ints."""
    if isinstance(item, tuple):
        return tuple(_norm_index(i) for i in item)
    if isinstance(item, Tensor):
        return item._value
    if isinstance(item, (list,)):
        return jnp.asarray(item)
    return item


def _getitem(self, item):
    idx = _norm_index(item)
    return apply_op(lambda v: v[idx], self)


def _setitem(self, item, value):
    idx = _norm_index(item)
    val = value._value if isinstance(value, Tensor) else value
    out = apply_op(lambda v, w: v.at[idx].set(w), self,
                   value if isinstance(value, Tensor) else Tensor(jnp.asarray(val)))
    self._value = out._value
    self._node = out._node
    return self
