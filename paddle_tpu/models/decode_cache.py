"""Bounded LRU memoization for decode-path compiled-fn factories.

gpt/moe_gpt memoize their jitted decode fns and on-device generate loops
keyed on (config, sampling knobs). The original module-level dicts grew
without bound — every distinct config/temperature/top_k combination pinned
its compiled executables (and their HBM constants) forever, a real leak
for long-lived serving processes that cycle model configs. Every such
cache now goes through ``DecodeFnCache``: an LRU bounded at ``maxsize``
entries whose evictions simply drop the reference (XLA frees the
executable with it), plus a weak global registry so tests can wipe every
decode cache in one call (``clear_decode_caches``)."""
import os
import threading
import weakref
from collections import OrderedDict

_REGISTRY = weakref.WeakSet()
_REGISTRY_LOCK = threading.Lock()


def _default_maxsize():
    try:
        v = int(os.environ.get('PADDLE_TPU_DECODE_CACHE_SIZE', 8))
    except ValueError:
        return 8
    return v if v > 0 else 8


class DecodeFnCache:
    """Thread-safe bounded LRU: ``get(key, builder)`` returns the cached
    value, building (and possibly evicting the least-recently-used entry)
    on miss. Instances register themselves weakly for
    ``clear_decode_caches``; per-model instances are collected normally."""

    def __init__(self, maxsize=None, name=None):
        self.maxsize = int(maxsize) if maxsize else _default_maxsize()
        if self.maxsize < 1:
            raise ValueError('maxsize must be >= 1')
        self.name = name or 'decode_cache'
        self._data = OrderedDict()
        self._lock = threading.RLock()
        with _REGISTRY_LOCK:
            _REGISTRY.add(self)

    def get(self, key, builder):
        with self._lock:
            if key in self._data:
                self._data.move_to_end(key)
                return self._data[key]
            value = builder()
            self._data[key] = value
            while len(self._data) > self.maxsize:
                self._data.popitem(last=False)
            return value

    def clear(self):
        with self._lock:
            self._data.clear()

    def __len__(self):
        with self._lock:
            return len(self._data)

    def __contains__(self, key):
        with self._lock:
            return key in self._data


def clear_decode_caches():
    """Drop every live decode-fn/generate-loop cache (module-level and
    per-model instances). Tests use this to force retraces; serving code
    can use it to release executables after a config rollover."""
    with _REGISTRY_LOCK:
        caches = list(_REGISTRY)
    for c in caches:
        c.clear()
