"""PP-YOLOE-lite-class single-stage detector: CSP-ish backbone + FPN-lite +
decoupled YOLO head, decoded by paddle_tpu.vision.ops.yolo_box + nms.

Reference capability: PP-YOLOE served through Paddle Inference static graphs.
"""
import paddle_tpu.nn as nn
from paddle_tpu.tensor.manipulation import concat
from paddle_tpu.nn.functional import interpolate


class ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, k=3, s=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=s, padding=k // 2,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.Silu()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class CSPBlock(nn.Layer):
    def __init__(self, c, n=1):
        super().__init__()
        self.cv1 = ConvBNAct(c, c // 2, 1)
        self.cv2 = ConvBNAct(c, c // 2, 1)
        self.m = nn.Sequential(*[ConvBNAct(c // 2, c // 2) for _ in range(n)])
        self.cv3 = ConvBNAct(c, c, 1)

    def forward(self, x):
        return self.cv3(concat([self.m(self.cv1(x)), self.cv2(x)], axis=1))


class PPYOLOELite(nn.Layer):
    def __init__(self, num_classes=80, width=32, num_anchors=3):
        super().__init__()
        w = width
        self.num_classes = num_classes
        self.num_anchors = num_anchors
        self.stem = ConvBNAct(3, w, 3, 2)                       # /2
        self.c2 = nn.Sequential(ConvBNAct(w, w * 2, 3, 2), CSPBlock(w * 2))    # /4
        self.c3 = nn.Sequential(ConvBNAct(w * 2, w * 4, 3, 2), CSPBlock(w * 4))  # /8
        self.c4 = nn.Sequential(ConvBNAct(w * 4, w * 8, 3, 2), CSPBlock(w * 8))  # /16
        self.c5 = nn.Sequential(ConvBNAct(w * 8, w * 16, 3, 2), CSPBlock(w * 16))  # /32
        self.lat5 = ConvBNAct(w * 16, w * 8, 1)
        self.lat4 = ConvBNAct(w * 16, w * 4, 1)
        out_ch = num_anchors * (5 + num_classes)
        self.head32 = nn.Conv2D(w * 8, out_ch, 1)
        self.head16 = nn.Conv2D(w * 4, out_ch, 1)

    def forward(self, x):
        x = self.stem(x)
        x = self.c2(x)
        c3 = self.c3(x)
        c4 = self.c4(c3)
        c5 = self.c5(c4)
        p5 = self.lat5(c5)
        up = interpolate(p5, scale_factor=2, mode='nearest')
        p4 = self.lat4(concat([up, c4], axis=1))
        return self.head32(p5), self.head16(p4)

    def decode(self, outs, img_size, conf_thresh=0.25):
        from paddle_tpu.vision.ops import yolo_box
        anchors32 = [116, 90, 156, 198, 373, 326]
        anchors16 = [30, 61, 62, 45, 59, 119]
        b32, s32 = yolo_box(outs[0], img_size, anchors32, self.num_classes,
                            conf_thresh, downsample_ratio=32)
        b16, s16 = yolo_box(outs[1], img_size, anchors16, self.num_classes,
                            conf_thresh, downsample_ratio=16)
        return concat([b32, b16], axis=1), concat([s32, s16], axis=1)
