"""PP-YOLOE-class anchor-free detector: CSP backbone + FPN + decoupled
ET-head with DFL box regression, trained by TAL assignment + VFL/GIoU/DFL.

Capability anchor: BASELINE.json names PP-YOLOE as a serving config; the
reference repo carries the op floor (vision/ops.py yolo_box/yolo_loss) and
PaddleDetection builds this head/loss stack on it. TPU-first: the head is
anchor-free (one cell = one prediction), regression is a distribution over
reg_max+1 integer bins decoded by a softmax expectation (one fused matmul),
and the whole loss — task-aligned assignment included — is static-shape
vectorized jax (vision/detection.py) that jits into a single XLA program.

The legacy anchor-based lite head remains available as ``PPYOLOELite`` for
yolo_box-style decode parity.
"""
import jax
import jax.numpy as jnp

import paddle_tpu.nn as nn
from paddle_tpu.core.dispatch import apply_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.tensor.manipulation import concat
from paddle_tpu.nn.functional import interpolate
from paddle_tpu.vision import detection as D


class ConvBNAct(nn.Layer):
    def __init__(self, cin, cout, k=3, s=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=s, padding=k // 2,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.Silu()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class CSPBlock(nn.Layer):
    def __init__(self, c, n=1):
        super().__init__()
        self.cv1 = ConvBNAct(c, c // 2, 1)
        self.cv2 = ConvBNAct(c, c // 2, 1)
        self.m = nn.Sequential(*[ConvBNAct(c // 2, c // 2) for _ in range(n)])
        self.cv3 = ConvBNAct(c, c, 1)

    def forward(self, x):
        return self.cv3(concat([self.m(self.cv1(x)), self.cv2(x)], axis=1))


class CSPBackbone(nn.Layer):
    """Shared stem + c2..c5 CSP pyramid (strides 2..32) used by both the
    anchor-free PPYOLOE and the legacy lite head."""

    def __init__(self, w):
        super().__init__()
        self.stem = ConvBNAct(3, w, 3, 2)                               # /2
        self.c2 = nn.Sequential(ConvBNAct(w, w * 2, 3, 2),
                                CSPBlock(w * 2))                        # /4
        self.c3 = nn.Sequential(ConvBNAct(w * 2, w * 4, 3, 2),
                                CSPBlock(w * 4))                        # /8
        self.c4 = nn.Sequential(ConvBNAct(w * 4, w * 8, 3, 2),
                                CSPBlock(w * 8))                        # /16
        self.c5 = nn.Sequential(ConvBNAct(w * 8, w * 16, 3, 2),
                                CSPBlock(w * 16))                       # /32

    def forward(self, x):
        x = self.c2(self.stem(x))
        c3 = self.c3(x)
        c4 = self.c4(c3)
        c5 = self.c5(c4)
        return c3, c4, c5


class ETHead(nn.Layer):
    """Decoupled per-level head: cls [B, C, H, W] + DFL reg
    [B, 4*(reg_max+1), H, W]."""

    def __init__(self, cin, num_classes, reg_max):
        super().__init__()
        self.cls_stem = ConvBNAct(cin, cin, 3)
        self.reg_stem = ConvBNAct(cin, cin, 3)
        self.cls = nn.Conv2D(cin, num_classes, 1)
        self.reg = nn.Conv2D(cin, 4 * (reg_max + 1), 1)

    def forward(self, x):
        return self.cls(self.cls_stem(x)), self.reg(self.reg_stem(x))


class PPYOLOE(nn.Layer):
    """Anchor-free PP-YOLOE-class detector over strides (8, 16, 32).

    forward(x [B,3,H,W]) -> per-level (cls_logits, reg_dist) pairs.
    loss(outs, gt_boxes [B,M,4] xyxy px, gt_labels [B,M], gt_mask [B,M])
    decode(outs, conf_thresh) -> (boxes [B,A,4], scores [B,A,C])
    """

    strides = (8, 16, 32)

    def __init__(self, num_classes=80, width=32, reg_max=16):
        super().__init__()
        w = width
        self.num_classes = num_classes
        self.reg_max = reg_max
        self.backbone = CSPBackbone(w)
        self.lat5 = ConvBNAct(w * 16, w * 8, 1)
        self.lat4 = ConvBNAct(w * 16, w * 4, 1)        # cat(up(p5), c4)
        self.lat3 = ConvBNAct(w * 8, w * 2, 1)         # cat(up(p4), c3)
        self.head8 = ETHead(w * 2, num_classes, reg_max)
        self.head16 = ETHead(w * 4, num_classes, reg_max)
        self.head32 = ETHead(w * 8, num_classes, reg_max)

    def forward(self, x):
        c3, c4, c5 = self.backbone(x)
        p5 = self.lat5(c5)
        p4 = self.lat4(concat([interpolate(p5, scale_factor=2,
                                           mode='nearest'), c4], axis=1))
        p3 = self.lat3(concat([interpolate(p4, scale_factor=2,
                                           mode='nearest'), c3], axis=1))
        return (self.head8(p3), self.head16(p4), self.head32(p5))

    # ---- functional core shared by loss and decode ----------------------
    # NOTE: flattening happens INSIDE the apply_op'd pure functions — the
    # head outputs enter as Tensors so the dygraph tape links the loss back
    # to every conv parameter (unwrapping first would detach them).

    def _flatten_raw(self, raw):
        """raw: [cls1, reg1, cls2, reg2, cls3, reg3] jax arrays ->
        (cls_logits [B, A, C], reg_dist [B, A, 4, reg_max+1],
        points [A, 2], stride_per_anchor [A])."""
        cls_l, reg_l, sizes = [], [], []
        for i in range(0, len(raw), 2):
            cv, rv = raw[i], raw[i + 1]
            B, C, H, W = cv.shape
            sizes.append((H, W))
            cls_l.append(cv.reshape(B, C, H * W).transpose(0, 2, 1))
            reg_l.append(rv.reshape(B, 4, self.reg_max + 1,
                                    H * W).transpose(0, 3, 1, 2))
        pts, sts = D.anchor_points(sizes, self.strides)
        return (jnp.concatenate(cls_l, 1), jnp.concatenate(reg_l, 1),
                pts, sts)

    def _boxes(self, reg_dist, pts, sts):
        """DFL distances -> xyxy boxes in input pixels."""
        ltrb = D.dfl_decode(reg_dist) * sts[None, :, None]
        return jnp.concatenate([pts[None] - ltrb[..., :2],
                                pts[None] + ltrb[..., 2:]], -1)

    def loss(self, outs, gt_boxes, gt_labels, gt_mask,
             loss_weights=(1.0, 2.5, 0.5)):
        """TAL-assigned VFL + GIoU + DFL total loss (scalar Tensor).
        gt_boxes: [B, M, 4] xyxy px (padded); gt_labels: [B, M] int;
        gt_mask: [B, M] bool (False rows are padding)."""
        num_classes = self.num_classes
        flatten, boxes_of = self._flatten_raw, self._boxes
        gb, gl, gm = [t._value if isinstance(t, Tensor) else jnp.asarray(t)
                      for t in (gt_boxes, gt_labels, gt_mask)]

        def pure(*raw):
            cls_logits, reg_dist, pts, sts = flatten(raw)
            pred_boxes = boxes_of(reg_dist, pts, sts)
            scores = jax.nn.sigmoid(cls_logits)

            def one(scores_i, boxes_i, cls_i, reg_i, gb_i, gl_i, gm_i):
                fg, lab, abox, ascore = D.task_aligned_assign(
                    jax.lax.stop_gradient(scores_i),
                    jax.lax.stop_gradient(boxes_i), pts, gb_i, gl_i, gm_i)
                # VFL target: assigned quality on the assigned class row
                onehot = jax.nn.one_hot(jnp.clip(lab, 0, num_classes - 1),
                                        num_classes)
                tgt = onehot * ascore[:, None] * fg[:, None]
                l_vfl = D.varifocal_loss(cls_i, tgt)
                w = ascore * fg                       # quality-weighted reg
                l_iou = jnp.sum(D.giou_loss(boxes_i, abox) * w)
                # DFL target: gt box as l/t/r/b bin distances at this cell
                ltrb_t = jnp.concatenate(
                    [pts - abox[:, :2], abox[:, 2:] - pts],
                    -1) / sts[:, None]
                l_dfl = jnp.sum(D.distribution_focal_loss(reg_i, ltrb_t)
                                * w[:, None])
                denom = jnp.maximum(jnp.sum(w), 1.0)
                return l_vfl / denom, l_iou / denom, l_dfl / (denom * 4.0)

            lv, li, ld = jax.vmap(one)(scores, pred_boxes, cls_logits,
                                       reg_dist, gb.astype(jnp.float32),
                                       gl.astype(jnp.int32),
                                       gm.astype(bool))
            wv, wi, wd = loss_weights
            return (wv * jnp.mean(lv) + wi * jnp.mean(li)
                    + wd * jnp.mean(ld))

        flat_outs = [t for pair in outs for t in pair]
        return apply_op(pure, *flat_outs)

    def decode(self, outs, conf_thresh=0.0):
        """-> (boxes [B, A, 4] xyxy px, scores [B, A, C]); fully traceable
        (compose with vision.ops.nms_static for a served graph).

        Sub-threshold scores are attenuated (x1e-4), not zeroed: zeroing
        would manufacture mass ties feeding the NMS sort, whose order is
        runtime-defined on external ONNX backends — attenuation keeps
        scores generically distinct so exported graphs rank
        deterministically (review r5d), while suppressed boxes still sort
        behind every real detection."""
        flatten, boxes_of = self._flatten_raw, self._boxes

        def pure(*raw):
            cl, rd, pts, sts = flatten(raw)
            boxes = boxes_of(rd, pts, sts)
            scores = jax.nn.sigmoid(cl)
            if conf_thresh:
                scores = jnp.where(scores >= conf_thresh, scores,
                                   scores * 1e-4)
            return boxes, scores

        flat_outs = [t for pair in outs for t in pair]
        return apply_op(pure, *flat_outs)


class PPYOLOELite(nn.Layer):
    """Legacy anchor-based lite detector (yolo_box decode parity path; the
    full-fidelity model above is PPYOLOE)."""

    def __init__(self, num_classes=80, width=32, num_anchors=3):
        super().__init__()
        w = width
        self.num_classes = num_classes
        self.num_anchors = num_anchors
        self.backbone = CSPBackbone(w)
        self.lat5 = ConvBNAct(w * 16, w * 8, 1)
        self.lat4 = ConvBNAct(w * 16, w * 4, 1)
        out_ch = num_anchors * (5 + num_classes)
        self.head32 = nn.Conv2D(w * 8, out_ch, 1)
        self.head16 = nn.Conv2D(w * 4, out_ch, 1)

    def forward(self, x):
        c3, c4, c5 = self.backbone(x)
        p5 = self.lat5(c5)
        up = interpolate(p5, scale_factor=2, mode='nearest')
        p4 = self.lat4(concat([up, c4], axis=1))
        return self.head32(p5), self.head16(p4)

    def decode(self, outs, img_size, conf_thresh=0.25):
        from paddle_tpu.vision.ops import yolo_box
        anchors32 = [116, 90, 156, 198, 373, 326]
        anchors16 = [30, 61, 62, 45, 59, 119]
        b32, s32 = yolo_box(outs[0], img_size, anchors32, self.num_classes,
                            conf_thresh, downsample_ratio=32)
        b16, s16 = yolo_box(outs[1], img_size, anchors16, self.num_classes,
                            conf_thresh, downsample_ratio=16)
        return concat([b32, b16], axis=1), concat([s32, s16], axis=1)
