"""GPT-style causal LM — the flagship training model.

Reference capability: PaddleNLP/Fleet GPT-3 hybrid parallel (the reference
repo's fleet meta_parallel stack, e.g.
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py
used by PaddleNLP gpt modeling). Re-designed TPU-first:

  - functional core: params are a pytree with transformer blocks STACKED on a
    leading layer dim and the forward a lax.scan over layers → one compiled
    block body regardless of depth (fast compiles, XLA-friendly)
  - bf16 activations/params option; fused QKV GEMM feeding the MXU
  - attention: Pallas flash attention on TPU (paddle_tpu.ops), XLA softmax
    fallback elsewhere
  - parallelism: dp (batch), mp (Megatron-style column/row sharding expressed
    as PartitionSpecs — XLA inserts the TP collectives), sp (ring attention
    over the sequence axis via shard_map), pp (GPipe microbatch pipeline via
    shard_map + ppermute), ZeRO opt-state sharding over dp
  - jax.checkpoint (remat) per block for memory at scale

The nn.Layer wrapper (GPTForCausalLM) exposes the paddle-style stateful API
over the same functional core.
"""
import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer_base import Layer, Parameter
from ..ops.weight_only import wo_lm_head, wo_matmul, wo_take


def validate_gqa(num_heads, num_kv_heads, mp):
    """Shared GQA/tensor-parallel config contract (GPT + MoE configs)."""
    kvh = num_kv_heads or num_heads
    if num_heads % kvh != 0:
        raise ValueError(
            f'num_kv_heads={kvh} must divide num_heads={num_heads}')
    if mp > 1 and (kvh % mp != 0 or num_heads % mp != 0):
        raise ValueError(
            f'mp={mp} must divide both num_heads={num_heads} and '
            f'num_kv_heads={kvh} (each tensor-parallel rank owns whole kv '
            'heads with their query groups)')


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    # GQA/MQA: kv heads (0 = MHA, one kv head per query head). Must divide
    # num_heads; with tensor parallel, mp must divide it too. The flash
    # kernels serve each kv head to its query group without repeating KV,
    # and the decode cache shrinks by num_heads/num_kv_heads.
    num_kv_heads: int = 0
    ffn_mult: int = 4
    max_seq_len: int = 1024
    dropout: float = 0.0
    dtype: str = 'bfloat16'
    param_dtype: str = 'float32'
    remat: bool = True
    # 'full': recompute everything (min memory); 'dots': save matmul/flash
    # outputs, recompute only cheap elementwise (near-full speed, ~matmul
    # activations memory) — the TPU sweet spot since MXU results are the
    # expensive thing to recompute and HBM is better spent on them.
    # Measured on v5e (tools/tpu_tune.py r4, 350M/seq1024): dots +1.5-3%
    # over full at modest extra HBM — the default
    remat_policy: str = 'dots'
    use_flash: bool = True
    # parallel degrees (must multiply to the mesh size together with dp)
    mp: int = 1
    pp: int = 1
    sp: int = 1
    n_microbatches: int = 1
    # 'gpipe': fwd scan + autodiff reverse pipeline (stores O(m) stage inputs)
    # '1f1b':  fused fwd/bwd schedule, O(pp) in-flight activations
    pp_schedule: str = 'gpipe'
    # blockwise LM-head cross-entropy chunk (0 disables): the loss streams
    # vocab chunks with an online logsumexp instead of materializing
    # [B,S,V] f32 logits (ops/xent.py). Auto-falls back when the vocab
    # doesn't tile or under mp/sp/pp sharded losses.
    xent_chunk: int = 8192
    # serving: store the KV cache as int8 with per-row scales — at long
    # context the cache, not the weights, is the decode step's biggest HBM
    # stream (ops/weight_only.quantize_kv; int8 flash decode kernel)
    kv_cache_int8: bool = False
    # lax.scan unroll over the layer stack (single-chip path): >1 lets XLA
    # software-pipeline across layer boundaries at the cost of program
    # size. Numerics are unchanged (tested); throughput is a chip-side
    # tuning knob (tools/tpu_tune.py --round3 rung).
    scan_unroll: int = 1
    # quantized dp-gradient all-reduce (distributed/quant_collectives,
    # EQuARX-style): 'none' keeps the full-width reduction; 'bf16' is the
    # cast fallback knob; 'int8'/'int4' move a block-scaled payload with
    # stochastic rounding; 'fp8' when the jax build has float8. Any value
    # but 'none' routes the train step through the explicit-collective
    # (shard_map) path so the reduction is addressable.
    grad_quant: str = 'none'
    # compute precision of the four block matmuls (qkv/proj/fc/out):
    # 'fp8' runs them e4m3-fwd/e5m2-bwd with per-tensor delayed scaling
    # (quantization/fp8.py); the train step then threads an fp8_state arg
    # (init_fp8_state) through the jitted step. Embedding, LM head and
    # norms stay full precision — they are a sliver of the FLOPs and the
    # loss is disproportionately sensitive to them.
    matmul_precision: str = 'none'

    def __post_init__(self):
        validate_gqa(self.num_heads, self.num_kv_heads, self.mp)
        if self.grad_quant not in ('none', 'bf16', 'int8', 'int4', 'fp8'):
            raise ValueError(
                f"grad_quant must be one of 'none'/'bf16'/'int8'/'int4'/"
                f"'fp8', got {self.grad_quant!r}")
        if self.matmul_precision not in ('none', 'fp8'):
            raise ValueError(
                f"matmul_precision must be 'none' or 'fp8', "
                f"got {self.matmul_precision!r}")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def ffn_size(self):
        return self.hidden_size * self.ffn_mult


def _split(key, n):
    return jax.random.split(key, n)


def init_params(config: GPTConfig, key):
    """Stacked-block param pytree."""
    h, f, v, L = (config.hidden_size, config.ffn_size, config.vocab_size,
                  config.num_layers)
    pdt = jnp.dtype(config.param_dtype)
    k = iter(_split(key, 8))
    std = 0.02

    def nrm(kk, shape, scale=std):
        return (scale * jax.random.normal(kk, shape)).astype(pdt)

    kb = _split(next(k), 6)
    # GQA: per-kv-head packing [q_0..q_{g-1}|k|v] -> (g+2)*kv_heads*hd cols
    qkv_cols = (config.num_heads + 2 * config.kv_heads) * config.head_dim
    blocks = {
        'ln1_g': jnp.ones((L, h), pdt), 'ln1_b': jnp.zeros((L, h), pdt),
        'qkv_w': nrm(kb[0], (L, h, qkv_cols)),
        'qkv_b': jnp.zeros((L, qkv_cols), pdt),
        'proj_w': nrm(kb[1], (L, h, h), std / math.sqrt(2 * L)),
        'proj_b': jnp.zeros((L, h), pdt),
        'ln2_g': jnp.ones((L, h), pdt), 'ln2_b': jnp.zeros((L, h), pdt),
        'fc_w': nrm(kb[2], (L, h, f)), 'fc_b': jnp.zeros((L, f), pdt),
        'out_w': nrm(kb[3], (L, f, h), std / math.sqrt(2 * L)),
        'out_b': jnp.zeros((L, h), pdt),
    }
    return {
        'wte': nrm(next(k), (v, h)),
        'wpe': nrm(next(k), (config.max_seq_len, h), 0.01),
        'blocks': blocks,
        'lnf_g': jnp.ones((h,), pdt), 'lnf_b': jnp.zeros((h,), pdt),
    }


# Logical axis names per parameter (parallel/partitioner.py): the Megatron
# column/row/pipeline layout is no longer written here as PartitionSpec
# literals — it falls out of one rules table ('heads'/'mlp' -> 'mp',
# 'layers' -> 'pp', 'vocab' -> 'mp' on the GSPMD path). 'positions' is
# deliberately unmapped: every sp rank slices its own rows from a full wpe.
LOGICAL_AXES = {
    'wte': ('vocab', 'embed'),
    'wpe': ('positions', 'embed'),
    'blocks': {
        'ln1_g': ('layers', 'embed'), 'ln1_b': ('layers', 'embed'),
        'qkv_w': ('layers', 'embed', 'heads'),
        'qkv_b': ('layers', 'heads'),
        'proj_w': ('layers', 'heads', 'embed'),
        'proj_b': ('layers', 'embed'),
        'ln2_g': ('layers', 'embed'), 'ln2_b': ('layers', 'embed'),
        'fc_w': ('layers', 'embed', 'mlp'), 'fc_b': ('layers', 'mlp'),
        'out_w': ('layers', 'mlp', 'embed'), 'out_b': ('layers', 'embed'),
    },
    'lnf_g': ('embed',), 'lnf_b': ('embed',),
}


def _partitioner(config: GPTConfig, explicit):
    from ..parallel.partitioner import Partitioner, model_rules
    return Partitioner(rules=model_rules(
        mp=config.mp, pp=config.pp, sp=config.sp, explicit=explicit))


def param_specs(config: GPTConfig):
    """PartitionSpecs for the GSPMD (jit + propagation) path, resolved from
    LOGICAL_AXES through the partitioner rules table."""
    return _partitioner(config, explicit=False).tree_specs(LOGICAL_AXES)


def _remat(body, config):
    """Apply the configured rematerialisation policy to a block body."""
    if config.remat_policy == 'dots':
        return jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    return jax.checkpoint(body)


def _layer_norm(x, g, b, eps=1e-5):
    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.mean(jnp.square(x - m), axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + eps) * g + b


def _attention(q, k, v, config, mesh=None, drop_seed=None):
    """q: [B, S, H, D]; k/v: [B, S, H_kv, D] (GQA: H_kv divides H). The
    flash kernels serve kv groups natively; the ring and einsum fallbacks
    repeat kv heads.

    drop_seed (traced u32, train-time only): config.dropout is sampled
    IN-KERNEL on the flash path (ops/flash_attention counter-hash; the
    jnp fallback applies the identical mask), so attention dropout never
    forces the XLA path (VERDICT r4 weak #8)."""
    # getattr: other configs sharing this attention core may predate the
    # dropout field (MoEConfig has it since r5; defensive for any future
    # config class)
    if getattr(config, 'dropout', 0.0) > 0.0 and drop_seed is not None:
        if config.sp > 1:
            from ..parallel.ring_attention import (ring_flash_attention,
                                                   ring_flash_available)
            if config.use_flash and ring_flash_available(q, k):
                # per-ring-pair masks regenerated in the backward sweep
                return ring_flash_attention(q, k, v, axis_name='sp',
                                            causal=True,
                                            drop_rate=config.dropout,
                                            seed=drop_seed)
            raise NotImplementedError(
                'attention dropout under sequence parallelism needs the '
                'ring flash path (use_flash=True, 128-multiple local '
                'shard) — or set dropout=0')
        if config.use_flash:
            from ..ops.flash_attention import flash_attention
            # falls back to the jnp path (same hash mask) on shapes or
            # platforms the kernels decline, so this is always safe
            return flash_attention(q, k, v, causal=True,
                                   dropout_rate=config.dropout,
                                   dropout_seed=drop_seed)
        from ..ops.flash_attention import _jnp_attention
        # use_flash=False is honored under dropout too (review r5f): the
        # jnp path samples the IDENTICAL counter-hash mask
        return _jnp_attention(q, k, v, True, None,
                              drop_rate=config.dropout, seed=drop_seed)
    if config.sp > 1:
        from ..parallel.ring_attention import (ring_attention,
                                               ring_flash_available,
                                               ring_flash_attention)
        if config.use_flash and ring_flash_available(q, k):
            # pallas kernels per ring pair: no S_local x S_local scores in
            # HBM, forward or backward; GQA kv blocks rotate un-repeated
            return ring_flash_attention(q, k, v, axis_name='sp', causal=True)
        from ..ops.flash_attention import repeat_kv
        k, v = repeat_kv(k, v, int(q.shape[2]))
        return ring_attention(q, k, v, axis_name='sp', causal=True)
    if config.use_flash:
        try:
            from ..ops.flash_attention import flash_attention, flash_attention_available
            if flash_attention_available(q, k, v, None):
                return flash_attention(q, k, v, causal=True)
        except Exception:
            pass
    from ..ops.flash_attention import repeat_kv
    k, v = repeat_kv(k, v, int(q.shape[2]))
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum('bqhd,bkhd->bhqk', q, k) * scale
    S = q.shape[1]
    mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
    s = jnp.where(mask, s, jnp.asarray(-1e30, s.dtype))
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)


def _mm(y, w, cdt, fp8_meta=None):
    """One block matmul: raw/weight-only via wo_matmul, or — when the
    caller threads an fp8 scaling meta — the e4m3/e5m2 delayed-scaling
    primitive (quantization/fp8.py)."""
    if fp8_meta is None:
        return wo_matmul(y, w, cdt)
    from ..quantization import fp8 as _fp8
    return _fp8.fp8_matmul(y, w.astype(cdt), fp8_meta)


def _block_qkv(bp, y, nh, hd, cdt, kvh=None, fp8_meta=None):
    """Fused QKV projection shared by the train block and the KV-cache
    decode block. Packing is per KV HEAD: [q_0..q_{g-1}|k|v] (g = query
    group size; g=1 is classic head-major MHA) — an 'mp' column shard is
    then exactly that rank's kv heads with their query groups (contiguous
    [Q|K|V] thirds would hand each rank a mix of Q and K columns)."""
    B, S, _ = y.shape
    kvh = nh if kvh is None else kvh
    g = nh // kvh
    qkv = _mm(y, bp['qkv_w'], cdt, fp8_meta) + bp['qkv_b'].astype(cdt)
    qkv = qkv.reshape(B, S, kvh, g + 2, hd)
    q = qkv[..., :g, :].reshape(B, S, nh, hd)
    return q, qkv[..., g, :], qkv[..., g + 1, :]


def _block_mlp(bp, y, cdt, fp8_fc=None, fp8_out=None):
    """fc -> gelu -> out projection (bias added by the caller after the
    mp all-reduce)."""
    y = jax.nn.gelu(_mm(y, bp['fc_w'], cdt, fp8_fc) + bp['fc_b'].astype(cdt))
    return _mm(y, bp['out_w'], cdt, fp8_out)


def block_fn(bp, x, config, explicit_mp=False, drop_seed=None,
             fp8_meta=None):
    """One transformer block. bp: this layer's params (no leading L dim).
    x: [B, S, H]. With ``explicit_mp`` (inside shard_map), qkv/fc weights are
    the local 'mp' shards and the two row-parallel matmuls psum over 'mp' —
    Megatron exactly as the reference's mp_layers, but via XLA collectives.
    """
    cdt = jnp.dtype(config.dtype)
    B, S, h = x.shape
    mp = config.mp if explicit_mp else 1
    nh, hd = config.num_heads // mp, config.head_dim
    kvh = config.kv_heads // mp

    if mp > 1:
        from ..parallel.tp_ad import f_identity, g_allreduce

    fm = fp8_meta or {}
    y = _layer_norm(x, bp['ln1_g'], bp['ln1_b']).astype(cdt)
    if mp > 1:
        y = f_identity(y, 'mp')
    q, k, v = _block_qkv(bp, y, nh, hd, cdt, kvh, fp8_meta=fm.get('qkv'))
    a = _attention(q, k, v, config,
                   drop_seed=drop_seed).reshape(B, S, h // mp)
    a = _mm(a, bp['proj_w'], cdt, fm.get('proj'))
    if mp > 1:
        a = g_allreduce(a, 'mp')
    x = x + a + bp['proj_b'].astype(cdt)

    y = _layer_norm(x, bp['ln2_g'], bp['ln2_b']).astype(cdt)
    if mp > 1:
        y = f_identity(y, 'mp')
    y = _block_mlp(bp, y, cdt, fp8_fc=fm.get('fc'), fp8_out=fm.get('out'))
    if mp > 1:
        y = g_allreduce(y, 'mp')
    x = x + y + bp['out_b'].astype(cdt)
    return x


def forward_hidden(params, tokens, config: GPTConfig, dropout_seed=None,
                   fp8_state=None):
    """tokens: [B, S] int32 -> final hidden states [B, S, H] (pre-LM-head).
    dropout_seed (traced u32 scalar, training only): enables
    config.dropout attention dropout with a distinct derived seed per
    layer; None (the serving/eval default) disables it with an UNCHANGED
    trace. fp8_state (init_fp8_state, training only): per-layer delayed
    scaling metas riding the scan xs next to the stacked block params —
    grads w.r.t. it are the UPDATED state (quantization/fp8.py)."""
    cdt = jnp.dtype(config.dtype)
    B, S = tokens.shape
    pos = jnp.arange(S)
    x = wo_take(params['wte'], tokens) + params['wpe'][pos]
    x = x.astype(cdt)

    body = partial(block_fn, config=config)
    if config.remat:
        body = _remat(body, config)

    use_drop = config.dropout > 0.0 and dropout_seed is not None
    if use_drop:
        # one derived seed per layer, riding the scan as an extra xs — the
        # scan call and epilogue below are shared with the no-dropout path
        from ..ops.flash_attention import per_layer_seeds
        seeds = per_layer_seeds(dropout_seed, config.num_layers)
    if use_drop and fp8_state is not None:
        xs = (params['blocks'], seeds, fp8_state['blocks'])

        def scan_body(carry, inp):
            bp, sd, fm = inp
            return body(bp, carry, drop_seed=sd, fp8_meta=fm), None
    elif use_drop:
        xs = (params['blocks'], seeds)

        def scan_body(carry, inp):
            bp, sd = inp
            return body(bp, carry, drop_seed=sd), None
    elif fp8_state is not None:
        xs = (params['blocks'], fp8_state['blocks'])

        def scan_body(carry, inp):
            bp, fm = inp
            return body(bp, carry, fp8_meta=fm), None
    else:
        xs = params['blocks']

        def scan_body(carry, bp):
            return body(bp, carry), None

    x, _ = jax.lax.scan(scan_body, x, xs,
                        unroll=max(1, int(config.scan_unroll)))
    return _layer_norm(x, params['lnf_g'], params['lnf_b']).astype(cdt)


def forward(params, tokens, config: GPTConfig, dropout_seed=None):
    """tokens: [B, S] int32 -> logits [B, S, V]. lax.scan over stacked blocks."""
    x = forward_hidden(params, tokens, config, dropout_seed=dropout_seed)
    return wo_lm_head(x, params['wte'], x.dtype)


def loss_fn(params, tokens, targets, config: GPTConfig, dropout_key=None,
            fp8_state=None):
    """dropout_key: PRNG key (train step's ``key``) — consumed only when
    config.dropout > 0 (the trace is unchanged otherwise). fp8_state: see
    forward_hidden."""
    seed = (jax.random.bits(dropout_key, (1,), jnp.uint32)[0]
            if config.dropout > 0.0 and dropout_key is not None else None)
    if (config.xent_chunk and config.mp == 1 and config.sp == 1
            and config.pp == 1
            and config.vocab_size % config.xent_chunk == 0):
        # blockwise LM-head loss: never materializes [B,S,V] logits (the
        # other HBM hog besides attention) — see ops/xent.py
        from ..ops.xent import softmax_xent_blockwise
        x = forward_hidden(params, tokens, config, dropout_seed=seed,
                           fp8_state=fp8_state)
        B, S, H = x.shape
        return softmax_xent_blockwise(x.reshape(B * S, H), params['wte'],
                                      targets.reshape(B * S),
                                      config.xent_chunk)
    x = forward_hidden(params, tokens, config, dropout_seed=seed,
                       fp8_state=fp8_state)
    logits = wo_lm_head(x, params['wte'], x.dtype)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


# fp8 training state -------------------------------------------------------

FP8_MATMULS = ('qkv', 'proj', 'fc', 'out')


def init_fp8_state(config: GPTConfig):
    """Delayed-scaling state for matmul_precision='fp8': one
    {x, w, g} x {scale, amax-history} meta per block matmul, stacked on
    the layer dim so it scans alongside params['blocks']. Pass it to the
    fp8 train step (make_train_step) as the third argument; the step
    returns the updated state in the same structure (donation-safe)."""
    from ..quantization import fp8 as _fp8
    return {'blocks': {name: _fp8.init_matmul_meta(config.num_layers)
                       for name in FP8_MATMULS}}


# ---------------------------------------------------------------------------
# KV-cache autoregressive decoding (serving path)
#
# TPU-native design: the cache is pre-allocated at [L, B, S_max, H, Dh]
# (static shapes — XLA compiles ONE prefill program and ONE decode-step
# program), each step writes its k/v row via lax.dynamic_update_slice and
# attends over the full cache with a position mask. Per-token cost is
# O(S_max * d) instead of the O(S^2 * d) full-context recompute, and the
# whole generate loop is a single lax.while-free python loop over ONE
# compiled step (no per-length retracing).
# ---------------------------------------------------------------------------

def quantize_decode_params(params):
    """Weight-only int8 snapshot of a GPT param pytree for serving (see
    ops/weight_only.py): the four block matrices and the tied embedding go
    int8 with per-output-channel (per-vocab-row for ``wte``) f32 scales;
    biases, norms and ``wpe`` stay as-is. The quantized pytree drops
    straight into ``forward`` / ``forward_with_cache`` — every weight
    consumer routes through the wo_* helpers — halving the HBM bytes the
    bandwidth-bound decode step must stream per token."""
    from ..ops.weight_only import quantize_weight
    blocks = dict(params['blocks'])
    for k in ('qkv_w', 'proj_w', 'fc_w', 'out_w'):
        blocks[k] = quantize_weight(blocks[k], reduce_axis=1)
    out = dict(params)
    out['blocks'] = blocks
    out['wte'] = quantize_weight(params['wte'], reduce_axis=1)
    return out


def init_kv_cache(config: GPTConfig, batch):
    """-> {'k','v': [L, B, S_max, H_kv, Dh] in the compute dtype}, or with
    ``config.kv_cache_int8`` each of k/v is ``{'int8': that shape int8,
    'scale': [L, B, S_max, H_kv] f32}`` (per-row quantization)."""
    cdt = jnp.dtype(config.dtype)
    shape = (config.num_layers, batch, config.max_seq_len,
             config.kv_heads, config.head_dim)
    if config.kv_cache_int8:
        from ..ops.weight_only import init_kv_bank
        return {'k': init_kv_bank(shape), 'v': init_kv_bank(shape)}
    return {'k': jnp.zeros(shape, cdt), 'v': jnp.zeros(shape, cdt)}


def is_paged(cache):
    """True when ``cache`` is a paged decode cache: ``{'k','v'}`` page
    pools (ops/paged_kv) plus a ``'page_table'`` [B, P_max] i32 and an
    optional ``'valid'`` [B] i32 (prefill: per-slot real prompt lengths,
    padding rows past it route to the trash page)."""
    return isinstance(cache, dict) and 'page_table' in cache


def init_paged_kv_cache(config, num_pages, page_size):
    """Shared page pool for the continuous-batching decode path:
    ``{'k','v': [L, num_pages, page_size, H_kv, Dh]}`` (int8 banks with
    ``config.kv_cache_int8``). Pair with a per-slot page table + ``pos``
    vector to form the paged cache ``forward_with_cache`` accepts; the
    dense ``init_kv_cache`` remains the default for ``generate()``."""
    from ..ops.paged_kv import init_paged_pool
    return init_paged_pool(config.num_layers, num_pages, page_size,
                           config.kv_heads, config.head_dim,
                           jnp.dtype(config.dtype),
                           int8=config.kv_cache_int8)


def cached_attention(x, q, k, v, k_cache, v_cache, pos, proj_w, proj_b, cdt,
                     page_table=None, valid=None, tail=False):
    """Shared KV-cache attention core (used by gpt AND moe_gpt decode):
    writes rows [pos, pos+T) into the caches, attends each q row to cache
    positions <= its absolute index, applies the output projection +
    residual. Returns (x_new, k_cache, v_cache). Caches may be raw
    ``[B, S_max, H_kv, D]`` arrays or int8 banks (init_kv_cache with
    ``kv_cache_int8``): fresh rows quantize on write and attention runs
    the int8 flash decode kernel (or a dequantizing fallback).

    Paged mode (``page_table`` not None): the caches are single-layer page
    pools ``[N, page_size, H_kv, D]`` (or int8 banks), ``pos`` is a [B]
    i32 vector (slots decode at different depths), and multi-token calls
    are prefills starting at position 0 per slot. Rows past ``valid[b]``
    are prompt padding and land in the trash page (ops/paged_kv).
    ``tail=True`` (static) marks a prefix-cache TAIL prefill: ``pos`` may
    be nonzero per slot and the q rows must attend KV already resident in
    earlier pages, so the fresh-rows causal-flash shortcut is invalid and
    attention runs over the paged cache."""
    from ..ops.weight_only import dequantize_kv, is_weight_only, quantize_kv
    B, T, h = x.shape
    if page_table is not None:
        from ..ops.paged_attention import paged_attention
        from ..ops.paged_kv import paged_write
        k_cache = paged_write(k_cache, k, page_table, pos, valid)
        v_cache = paged_write(v_cache, v, page_table, pos, valid)
        from ..ops.flash_attention import (flash_attention,
                                           flash_attention_available)
        if T > 1 and not tail and flash_attention_available(q, k, v, None):
            # multi-token paged calls are engine prefills from position 0:
            # attention over the paged cache equals causal self-attention
            # over the fresh rows (padding rows only feed padding rows,
            # which the engine discards) — run the main flash kernel
            # instead of gathering the virtual cache. A TAIL prefill
            # (tail=True) starts mid-sequence and must see the cached
            # prefix pages, so it takes the paged path below.
            a = flash_attention(q, k, v, causal=True).reshape(B, T, h)
        else:
            a = paged_attention(q, k_cache, v_cache, page_table, pos,
                                cdt).reshape(B, T, h)
        return (x + wo_matmul(a, proj_w, cdt) + proj_b.astype(cdt),
                k_cache, v_cache)
    int8_cache = is_weight_only(k_cache)
    if int8_cache:
        def write(bank, rows):
            qr, sr = quantize_kv(rows)
            return {'int8': jax.lax.dynamic_update_slice(
                        bank['int8'], qr, (0, pos, 0, 0)),
                    'scale': jax.lax.dynamic_update_slice(
                        bank['scale'], sr.astype(bank['scale'].dtype),
                        (0, pos, 0))}
        k_cache, v_cache = write(k_cache, k), write(v_cache, v)
    else:
        k_cache = jax.lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
    from ..ops.flash_attention import (
        flash_attention, flash_attention_available, flash_decode,
        flash_decode_available, flash_decode_int8)
    k_arr = k_cache['int8'] if int8_cache else k_cache
    if (isinstance(pos, int) and pos == 0
            and flash_attention_available(q, k, v, None)):
        # prefill at a STATIC position 0: attention over the cache equals
        # causal self-attention over the fresh k/v (later cache rows are
        # masked out anyway) — run the main flash kernel
        a = flash_attention(q, k, v, causal=True).reshape(B, T, h)
    elif flash_decode_available(q, k_arr):
        # pallas decode kernel: streams only cache blocks up to ``pos``
        a = (flash_decode_int8(q, k_cache, v_cache, pos) if int8_cache
             else flash_decode(q, k_cache, v_cache, pos)).reshape(B, T, h)
    else:
        from ..ops.flash_attention import repeat_kv
        if int8_cache:
            kc = dequantize_kv(k_cache['int8'], k_cache['scale'], cdt)
            vc = dequantize_kv(v_cache['int8'], v_cache['scale'], cdt)
        else:
            kc, vc = k_cache, v_cache
        k_cache_a, v_cache_a = repeat_kv(kc, vc, int(q.shape[2]))
        S = k_arr.shape[1]
        scale = 1.0 / math.sqrt(q.shape[-1])
        s = jnp.einsum('bqhd,bkhd->bhqk', q, k_cache_a) * scale  # [B,H,T,S]
        q_pos = pos + jnp.arange(T)[:, None]                    # [T,1]
        k_pos = jnp.arange(S)[None, :]                          # [1,S]
        s = jnp.where((k_pos <= q_pos)[None, None], s.astype(jnp.float32),
                      jnp.float32(-1e30))
        p = jax.nn.softmax(s, axis=-1).astype(cdt)
        a = jnp.einsum('bhqk,bkhd->bqhd', p, v_cache_a).reshape(B, T, h)
    return (x + wo_matmul(a, proj_w, cdt) + proj_b.astype(cdt),
            k_cache, v_cache)


def _cached_block(bp, x, k_cache, v_cache, pos, config, page_table=None,
                  valid=None, tail=False):
    """One block over a [B, T, H] slice starting at ``pos``."""
    cdt = jnp.dtype(config.dtype)
    y = _layer_norm(x, bp['ln1_g'], bp['ln1_b']).astype(cdt)
    q, k, v = _block_qkv(bp, y, config.num_heads, config.head_dim, cdt,
                         config.kv_heads)
    x, k_cache, v_cache = cached_attention(
        x, q, k, v, k_cache, v_cache, pos, bp['proj_w'], bp['proj_b'], cdt,
        page_table=page_table, valid=valid, tail=tail)
    y = _layer_norm(x, bp['ln2_g'], bp['ln2_b']).astype(cdt)
    x = x + _block_mlp(bp, y, cdt) + bp['out_b'].astype(cdt)
    return x, k_cache, v_cache


def paged_forward_with_cache(params, tokens, cache, pos, config,
                             last_only=False, block=_cached_block,
                             partitioner=None):
    """Paged-cache twin of ``forward_with_cache``: ``cache`` carries the
    page pools + ``page_table`` (+ optional ``valid``), ``pos`` is a [B]
    i32 vector. ``block`` lets moe_gpt reuse this driver with its own
    block body. Returns (logits, cache) with the table/valid passed
    through so the caller's cache pytree keeps one structure.

    ``partitioner`` (a mesh-bound parallel.Partitioner) makes the trace
    mesh-aware: the KV pool planes are constrained to the ``kv_heads``
    layout on entry AND exit, so GSPMD keeps pages head-sharded across the
    whole layer scan instead of resharding KV around the attention
    collectives (parallel/mesh_engine.py; a None partitioner — the mp=1
    path — traces byte-identically to before)."""
    cdt = jnp.dtype(config.dtype)
    B, T = tokens.shape
    pos_v = jnp.asarray(pos, jnp.int32).reshape(-1)
    page_table = cache['page_table']
    valid = cache.get('valid')

    def pin_pool(plane):
        # int8 pools are {'int8','scale'} banks whose scale plane drops
        # the head_dim axis — only the raw 5-d layout is pinned (banks
        # still shard correctly via input-sharding propagation)
        if partitioner is None or getattr(plane, 'ndim', 0) != 5:
            return plane
        from ..ops.paged_kv import POOL_LOGICAL_AXES
        return jax.lax.with_sharding_constraint(
            plane, partitioner.sharding(POOL_LOGICAL_AXES))

    cache = dict(cache, k=pin_pool(cache['k']), v=pin_pool(cache['v']))
    # STATIC marker set by the prefix-cache tail-prefill path (the engine
    # builds the cache dict in-trace, so a plain bool survives): q rows
    # must attend KV resident in earlier pages, not just the fresh rows
    tail = bool(cache.get('tail', False))
    ppos = jnp.clip(pos_v[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :],
                    0, config.max_seq_len - 1)            # [B, T]
    x = (wo_take(params['wte'], tokens)
         + jnp.take(params['wpe'], ppos, axis=0)).astype(cdt)

    def scan_body(carry, inp):
        xx = carry
        bp, kc, vc = inp
        xx, kc, vc = block(bp, xx, kc, vc, pos_v, config,
                           page_table=page_table, valid=valid, tail=tail)
        return xx, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        scan_body, x, (params['blocks'], cache['k'], cache['v']))
    k_new, v_new = pin_pool(k_new), pin_pool(v_new)
    if last_only:
        if valid is not None:
            # per-slot prompt lengths: pick each slot's last REAL row
            idx = jnp.clip(valid.astype(jnp.int32) - 1, 0, T - 1)
            x = jnp.take_along_axis(x, idx[:, None, None], axis=1)
        else:
            x = x[:, -1:]
    x = _layer_norm(x, params['lnf_g'], params['lnf_b']).astype(cdt)
    logits = wo_lm_head(x, params['wte'], cdt)
    out = {'k': k_new, 'v': v_new, 'page_table': page_table}
    if valid is not None:
        out['valid'] = valid
    return logits, out


def forward_with_cache(params, tokens, cache, pos, config: GPTConfig,
                       last_only=False, partitioner=None):
    """Run [B, T] tokens whose absolute positions start at ``pos`` (a traced
    scalar), reading/writing the KV cache. Returns (logits, cache) — logits
    [B,T,V], or [B,1,V] with ``last_only`` (prefill skips the full-vocab
    head matmul for all but the final position: at B=8, T0=1000, V=50304
    that matmul and its ~1.6 GB logits tensor are pure waste).
    T is the static block width: the prompt length at prefill, 1 per decode
    step — each width compiles exactly once.

    A paged cache (``is_paged``: page pools + ``page_table``) routes to
    ``paged_forward_with_cache`` with ``pos`` as a per-slot [B] vector;
    the dense contiguous cache stays the default. ``partitioner`` (mesh-
    bound, serving over an mp=N mesh) pins the paged pool to the
    ``kv_heads`` layout — see paged_forward_with_cache."""
    if is_paged(cache):
        return paged_forward_with_cache(params, tokens, cache, pos, config,
                                        last_only=last_only,
                                        partitioner=partitioner)
    cdt = jnp.dtype(config.dtype)
    B, T = tokens.shape
    ppos = pos + jnp.arange(T)
    x = (wo_take(params['wte'], tokens)
         + jnp.take(params['wpe'], ppos, axis=0)).astype(cdt)

    def scan_body(carry, inp):
        xx = carry
        bp, kc, vc = inp
        xx, kc, vc = _cached_block(bp, xx, kc, vc, pos, config)
        return xx, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        scan_body, x, (params['blocks'], cache['k'], cache['v']))
    if last_only:
        x = x[:, -1:]
    x = _layer_norm(x, params['lnf_g'], params['lnf_b']).astype(cdt)
    logits = wo_lm_head(x, params['wte'], cdt)
    return logits, {'k': k_new, 'v': v_new}


def _sample(logits, temperature, top_k, top_p=None, key=None):
    """Greedy / temperature / top-k / nucleus next-token draw — the ONE
    sampling rule shared by the cache path and the sliding-window
    continuation. ``key`` overrides the global PRNG stream (reproducible
    functional sampling). top_k and top_p compose (intersection), as in
    the reference generation utilities."""
    if temperature == 0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        from ..tensor.random import next_key
        key = next_key()
    lg = logits.astype(jnp.float32) / temperature
    nucleus = top_p is not None and top_p < 1.0
    if top_k or nucleus:
        # ONE descending sort serves both filters (per-token decode path)
        srt = jnp.sort(lg, axis=-1)[:, ::-1]
        if top_k:
            kth = srt[:, top_k - 1][:, None]
            lg = jnp.where(lg < kth, -jnp.inf, lg)
            srt = jnp.where(jnp.arange(srt.shape[-1]) < top_k, srt, -jnp.inf)
        if nucleus:
            # keep the smallest prefix of the sorted (already top_k-masked)
            # distribution whose cumulative prob reaches top_p; the argmax
            # is ALWAYS kept (exclusive cumsum + explicit index-0 set, so
            # top_p <= 0 degrades to greedy, not to all -inf)
            probs = jax.nn.softmax(srt, axis=-1)
            keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
            keep = keep.at[:, 0].set(True)
            cut = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                          keepdims=True)
            lg = jnp.where(lg < cut, -jnp.inf, lg)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


def make_generate_loop(config, temperature=0.0, top_k=None, top_p=None,
                       forward_fn=None):
    """On-device autoregressive generation: ONE jitted program runs
    ``n_steps`` KV-cache decode steps via lax.scan (sampling included), so
    the whole loop costs a single dispatch instead of one host round-trip
    per token. On the axon tunnel (30-70 ms RTT per dispatch) the per-token
    python loop was dispatch-bound at ~71 steps/s — ~13%% of the HBM
    roofline the decode step can actually sustain (VERDICT r4 weak #4).

    -> gen(params, tok0 [B] i32, pos0 i32, cache, key, n_steps static)
       returning (tokens [B, n_steps] i32, cache). ``tok0`` is consumed as
    the input of the first step; the sample drawn from each step's logits
    is both emitted and fed to the next step.

    forward_fn(params, tokens, cache, pos, config) -> (logits, cache)
    defaults to this module's forward_with_cache; moe_gpt passes its own,
    sharing this one loop implementation.
    """
    fwd = forward_fn or forward_with_cache

    def gen(params, tok0, pos0, cache, key, n_steps):
        def body(carry, step_key):
            tok, pos, cache = carry
            logits, cache = fwd(params, tok[:, None], cache, pos, config)
            lg = logits[:, 0] if logits.ndim == 3 else logits
            nxt = _sample(lg, temperature, top_k, top_p, key=step_key)
            return (nxt, pos + 1, cache), nxt

        keys = jax.random.split(key, n_steps)
        (tok, pos, cache), toks = jax.lax.scan(
            body, (tok0, pos0, cache), keys)
        return jnp.swapaxes(toks, 0, 1), cache

    return jax.jit(gen, static_argnums=(5,), donate_argnums=(3,))


def make_decode_fns(config: GPTConfig):
    """-> (prefill, step), both jitted with donated caches.

    prefill(params, prompt [B,T], cache) -> (last_logits [B,V], cache)
    step(params, tok [B], pos, cache)    -> (logits [B,V], cache)
    """
    @partial(jax.jit, donate_argnums=(2,))
    def prefill(params, prompt, cache):
        logits, cache = forward_with_cache(params, prompt, cache,
                                           jnp.int32(0), config,
                                           last_only=True)
        return logits[:, -1], cache

    @partial(jax.jit, donate_argnums=(3,))
    def step(params, tok, pos, cache):
        logits, cache = forward_with_cache(params, tok[:, None], cache, pos,
                                           config)
        return logits[:, 0], cache

    return prefill, step


# ---------------------------------------------------------------------------
# Hybrid-parallel train step
# ---------------------------------------------------------------------------

def _uses_shard_map(config: GPTConfig):
    """Explicit-collective path: sp ring / pp pipeline schedules, or a
    quantized gradient all-reduce (which needs an addressable dp psum)."""
    return (config.sp > 1 or config.pp > 1
            or getattr(config, 'grad_quant', 'none') not in (None, 'none'))


def make_train_step(config: GPTConfig, optimizer, mesh=None):
    """Returns jitted step(params, opt_state, key, lr, tokens, targets) ->
    (loss, params, opt_state) sharded over the mesh. Shardings:
      params per param_specs (mp/pp), batch over ('dp',), sequence over 'sp'
      (ring attention), opt state ZeRO-sharded over dp when configured.
    config.grad_quant != 'none' reduces dp gradients through
    distributed/quant_collectives (block-scaled int8/int4/fp8 or the bf16
    fallback) instead of the full-width pmean.
    """
    from ..distributed.topology import get_mesh
    mesh = mesh or get_mesh()
    specs = param_specs(config)
    quant = getattr(config, 'grad_quant', 'none') or 'none'

    use_shard_map = _uses_shard_map(config)
    if config.dropout > 0.0 and config.pp > 1:
        # the pipeline loss paths do not sample dropout; silently training
        # a different model than configured is the r4-journey bug class —
        # refuse loudly (sp rides the ring kernels' in-kernel masks; dp/mp
        # ride the GSPMD path)
        raise NotImplementedError(
            'attention dropout under pipeline parallelism is not '
            'implemented — set dropout=0, or use dp/mp/sp layouts')

    fp8 = getattr(config, 'matmul_precision', 'none') == 'fp8'
    if fp8 and use_shard_map:
        raise NotImplementedError(
            "matmul_precision='fp8' under the explicit-collective "
            '(shard_map) layouts (sp/pp/grad_quant) is not implemented — '
            'use the GSPMD dp/mp path or matmul_precision=none')

    if fp8:
        # fp8 step: the delayed-scaling state is an explicit third arg and
        # output — step(params, opt_state, fp8_state, key, lr, tokens,
        # targets) -> (loss, params, opt_state, fp8_state). The new state
        # arrives as the GRADIENT of the old one (quantization/fp8.py), so
        # one backward pass yields grads and state with no side channel,
        # no host sync, and donation-compatible buffers.
        def step(params, opt_state, fp8_state, key, lr, tokens, targets):
            loss, (grads, new_fp8) = jax.value_and_grad(
                lambda p, f8: loss_fn(p, tokens, targets, config,
                                      key if config.dropout > 0.0 else None,
                                      fp8_state=f8),
                argnums=(0, 1))(params, fp8_state)
            new_p, new_s = optimizer.functional_apply(params, grads,
                                                      opt_state, lr)
            return loss, new_p, new_s, new_fp8
        return jax.jit(step, donate_argnums=(0, 1, 2))

    if not use_shard_map:
        def step(params, opt_state, key, lr, tokens, targets):
            # the step's key drives attention dropout when configured
            # (config.dropout == 0 leaves the trace unchanged)
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, targets, config,
                key if config.dropout > 0.0 else None)
            new_p, new_s = optimizer.functional_apply(params, grads, opt_state, lr)
            return loss, new_p, new_s
        return jax.jit(step, donate_argnums=(0, 1))

    # Explicit-collective path (shard_map over dp/sp/pp/mp): Megatron mp via
    # psum in block_fn, ring attention over sp, GPipe microbatch over pp.
    from jax.experimental.shard_map import shard_map
    from ..parallel.pipeline import pipeline_apply, last_stage_mask

    explicit_mp = config.mp > 1

    if config.pp > 1 and config.pp_schedule == '1f1b':
        if quant != 'none':
            raise NotImplementedError(
                'grad_quant under the fused 1F1B schedule is not '
                "implemented — use pp_schedule='gpipe' or grad_quant='none'")
        return _make_train_step_1f1b(config, optimizer, mesh, explicit_mp)

    def spmd_loss(params, tokens, targets, seed=None):
        cdt = jnp.dtype(config.dtype)
        B, S = tokens.shape
        sp_idx = jax.lax.axis_index('sp') if config.sp > 1 else 0
        pos = sp_idx * S + jnp.arange(S)
        x = jnp.take(params['wte'], tokens, axis=0) + params['wpe'][pos]
        x = x.astype(cdt)

        body = partial(block_fn, config=config, explicit_mp=explicit_mp)
        if config.remat:
            body = _remat(body, config)

        if config.dropout > 0.0 and seed is not None:
            # decorrelate ranks whose kernels see identical LOCAL
            # coordinates (dp batch shards; mp head shards), then one
            # derived seed per layer (same scheme as forward_hidden); the
            # sp ring folds its own (q rank, kv rank) pair into the seed.
            # every fold is mix_seed'd — nonlinear, so index strides can
            # never alias the hash's coordinate multipliers (review r5h)
            from ..ops.flash_attention import mix_seed, per_layer_seeds
            seed_eff = mix_seed(
                jnp.asarray(seed, jnp.uint32)
                + jnp.asarray(jax.lax.axis_index('dp'), jnp.uint32)
                * jnp.uint32(0x165667B1))
            if explicit_mp:
                seed_eff = mix_seed(
                    seed_eff + jnp.asarray(jax.lax.axis_index('mp'),
                                           jnp.uint32)
                    * jnp.uint32(0xD3A2646D))
            seeds = per_layer_seeds(seed_eff, config.num_layers)
            xs = (params['blocks'], seeds)

            def scan_body(c, inp):
                bp, sd = inp
                return body(bp, c, drop_seed=sd), None
        else:
            xs = params['blocks']

            def scan_body(c, bp):
                return body(bp, c), None

        if config.pp > 1:
            def stage_fn(stage_params, xx):
                out, _ = jax.lax.scan(scan_body, xx, stage_params)
                return out
            x = pipeline_apply(stage_fn, params['blocks'], x,
                               config.n_microbatches, axis_name='pp')
        else:
            x, _ = jax.lax.scan(scan_body, x, xs)

        x = _layer_norm(x, params['lnf_g'], params['lnf_b']).astype(cdt)
        logits = x @ params['wte'].T.astype(cdt)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        loss = -jnp.mean(ll)
        if config.pp > 1:
            # head/loss are only valid on the last stage; the psum over 'pp'
            # happens AFTER the vjp (in spmd_valgrad) so no collective with an
            # ambiguous transpose sits inside the differentiated region
            loss = jnp.where(last_stage_mask('pp'), loss, 0.0)
        return loss

    def spmd_valgrad(params, tokens, targets, seed=None):
        """value+grad INSIDE shard_map: the only collectives the vjp sees are
        ppermute (pipeline/ring — exact inverse-permutation transpose) and the
        custom-vjp Megatron f/g pair, so grads are exact per rank. Cross-rank
        reductions are applied explicitly afterwards — which is what makes
        the dp gradient reduction addressable for quant_collectives."""
        drop_seed = seed if config.dropout > 0.0 else None
        loss, grads = jax.value_and_grad(
            lambda p: spmd_loss(p, tokens, targets, drop_seed))(params)
        if config.pp > 1:
            # shared (non-block) params: embedding grads live on stage 0,
            # head grads on the last stage → assemble across stages
            loss = jax.lax.psum(loss, 'pp')
            grads = {k: (v if k == 'blocks' else
                         jax.tree_util.tree_map(
                             lambda g: jax.lax.psum(g, 'pp'), v))
                     for k, v in grads.items()}
        reduce_axes = ['dp'] + (['sp'] if config.sp > 1 else [])
        for ax in reduce_axes:
            loss = jax.lax.pmean(loss, ax)
            if ax == 'dp' and quant != 'none':
                from ..distributed import quant_collectives as qc
                from ..ops.flash_attention import mix_seed
                qseed = None
                if seed is not None:
                    # decorrelate the rounding stream from the dropout
                    # stream sharing the same step seed
                    qseed = mix_seed(jnp.asarray(seed, jnp.uint32)
                                     ^ jnp.uint32(0xA5A5F00D))
                grads = qc.psum_tree(grads, 'dp', mode=quant,
                                     seed=qseed,
                                     stochastic=qseed is not None,
                                     mean=True)
            else:
                grads = jax.tree_util.tree_map(
                    lambda g, _ax=ax: jax.lax.pmean(g, _ax), grads)
        return loss, grads

    pspec_tree = train_specs(config)
    data_spec = _partitioner(config, explicit=True).spec(('batch', 'length'))

    # a seed rides the step key into shard_map when anything inside needs
    # per-step randomness: attention dropout, or stochastic rounding in the
    # quantized gradient all-reduce
    needs_seed = config.dropout > 0.0 or quant in ('int8', 'int4')
    if needs_seed:
        smapped = shard_map(spmd_valgrad, mesh=mesh,
                            in_specs=(pspec_tree, data_spec, data_spec,
                                      P()),
                            out_specs=(P(), pspec_tree), check_rep=False)

        def step(params, opt_state, key, lr, tokens, targets):
            seed = jax.random.bits(key, (), jnp.uint32)
            loss, grads = smapped(params, tokens, targets, seed)
            new_p, new_s = optimizer.functional_apply(params, grads,
                                                      opt_state, lr)
            return loss, new_p, new_s

        return jax.jit(step, donate_argnums=(0, 1))

    smapped = shard_map(spmd_valgrad, mesh=mesh,
                        in_specs=(pspec_tree, data_spec, data_spec),
                        out_specs=(P(), pspec_tree), check_rep=False)

    def step(params, opt_state, key, lr, tokens, targets):
        loss, grads = smapped(params, tokens, targets)
        new_p, new_s = optimizer.functional_apply(params, grads, opt_state, lr)
        return loss, new_p, new_s

    return jax.jit(step, donate_argnums=(0, 1))


def _make_train_step_1f1b(config: GPTConfig, optimizer, mesh, explicit_mp):
    """Fused 1F1B pipeline train step: manual fwd+bwd via
    parallel.pipeline.pipeline_train_1f1b (O(pp) in-flight activations), no
    outer jax.grad. Reference: fleet pipeline_parallel.py 1F1B scheduler."""
    from jax.experimental.shard_map import shard_map
    from ..parallel.pipeline import pipeline_train_1f1b

    shared_keys = ('wte', 'wpe', 'lnf_g', 'lnf_b')

    def spmd_grads(params, tokens, targets):
        cdt = jnp.dtype(config.dtype)
        shared = {k: params[k] for k in shared_keys}

        def embed_fn(sh, tok):
            S = tok.shape[1]
            sp_idx = jax.lax.axis_index('sp') if config.sp > 1 else 0
            pos = sp_idx * S + jnp.arange(S)
            return (jnp.take(sh['wte'], tok, axis=0)
                    + sh['wpe'][pos]).astype(cdt)

        body = partial(block_fn, config=config, explicit_mp=explicit_mp)
        if config.remat:
            body = _remat(body, config)

        def stage_fn(stage_params, xx):
            out, _ = jax.lax.scan(lambda c, bp: (body(bp, c), None),
                                  xx, stage_params)
            return out

        def head_fn(sh, h, tgt):
            x = _layer_norm(h, sh['lnf_g'], sh['lnf_b']).astype(cdt)
            logits = x @ sh['wte'].T.astype(cdt)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
            return -jnp.mean(ll)

        loss, g_blocks, g_shared = pipeline_train_1f1b(
            stage_fn, embed_fn, head_fn, params['blocks'], shared,
            tokens, targets, config.n_microbatches, axis_name='pp')

        grads = dict(g_shared)
        grads['blocks'] = g_blocks
        loss = jax.lax.pmean(loss, 'dp')
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.pmean(g, 'dp'), grads)
        if config.sp > 1:
            loss = jax.lax.pmean(loss, 'sp')
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, 'sp'), grads)
        return loss, grads

    pspec_tree = train_specs(config)
    data_spec = _partitioner(config, explicit=True).spec(('batch', 'length'))
    smapped = shard_map(spmd_grads, mesh=mesh,
                        in_specs=(pspec_tree, data_spec, data_spec),
                        out_specs=(P(), pspec_tree), check_rep=False)

    def step(params, opt_state, key, lr, tokens, targets):
        loss, grads = smapped(params, tokens, targets)
        new_p, new_s = optimizer.functional_apply(params, grads, opt_state, lr)
        return loss, new_p, new_s

    return jax.jit(step, donate_argnums=(0, 1))


def train_specs(config: GPTConfig):
    """PartitionSpecs matching what make_train_step expects for params:
    the explicit-collective (shard_map) rules when the step uses that path
    — per-rank views, vocab replicated — otherwise the GSPMD rules. Both
    resolve LOGICAL_AXES through the same partitioner rules table."""
    explicit = _uses_shard_map(config)
    return _partitioner(config, explicit=explicit).tree_specs(LOGICAL_AXES)


def place_params(params, config, mesh):
    specs = train_specs(config)

    def put(x, s):
        try:
            return jax.device_put(x, NamedSharding(mesh, s))
        except Exception:
            return x
    return jax.tree_util.tree_map(put, params, specs)


# ---------------------------------------------------------------------------
# Layer-API wrapper
# ---------------------------------------------------------------------------

class GPTForCausalLM(Layer):
    """Stateful paddle-style wrapper over the functional core."""

    def __init__(self, config: GPTConfig = None, **kwargs):
        super().__init__()
        self.config = config or GPTConfig(**kwargs)
        from ..tensor.random import next_key
        raw = init_params(self.config, next_key())
        leaves, treedef = jax.tree_util.tree_flatten(raw)
        self._treedef = treedef
        self._n = len(leaves)
        for i, leaf in enumerate(leaves):
            self.add_parameter(f'p{i}', Parameter(leaf))

    def _params(self):
        return jax.tree_util.tree_unflatten(
            self._treedef, [self._parameters[f'p{i}']._value
                            for i in range(self._n)])

    def forward(self, tokens):
        from ..core.dispatch import apply_op
        cfg = self.config
        plist = [self._parameters[f'p{i}'] for i in range(self._n)]
        treedef = self._treedef

        def pure(tok, *leaves):
            params = jax.tree_util.tree_unflatten(treedef, list(leaves))
            return forward(params, jnp.asarray(tok).astype(jnp.int32), cfg)
        return apply_op(pure, tokens, *plist)

    def generate(self, tokens, max_new_tokens=32, temperature=1.0,
                 top_k=None, top_p=None):
        """KV-cache autoregressive sampling: one compiled prefill + ONE
        on-device generation loop (make_generate_loop) that runs all cached
        decode steps in a single dispatch — O(S_max d) per token, with loop
        lengths bucketed to powers of two so varying lengths reuse a small
        set of compiled programs. Tokens past the context window continue
        on the sliding-window recompute path, so the cache is used for
        every token that fits it."""
        cfg = self.config
        toks = tokens._value if isinstance(tokens, Tensor) else jnp.asarray(tokens)
        toks = toks.astype(jnp.int32)
        B, T0 = toks.shape
        # +1: the final cached step runs at pos max_seq_len-1 (filling the
        # last cache row) and its logits see the full window — identical
        # conditioning to the sliding path's first step
        n_cached = (min(max_new_tokens, cfg.max_seq_len - T0 + 1)
                    if T0 < cfg.max_seq_len else 0)
        if n_cached > 0:
            params = self._decode_params()
            prefill, step = self._decode_fns()
            cache = init_kv_cache(cfg, B)
            logits, cache = prefill(params, toks, cache)
            first = _sample(logits, temperature, top_k, top_p)
            pieces = [toks, first[:, None]]
            if n_cached > 1:
                # all remaining cached tokens run on-device in one dispatch
                # (make_generate_loop); greedy tokens are bit-identical to
                # the per-step python loop this replaces. The step count is
                # bucketed to the next power of two (excess tokens dropped)
                # so varying prompt/max_new lengths reuse a handful of
                # compiled programs instead of retracing per length; extra
                # steps may clamp at the last cache row, which only affects
                # the discarded tail.
                loop = self._generate_loop(temperature, top_k, top_p)
                n = n_cached - 1
                bucket = 1 << (n - 1).bit_length() if n > 1 else 1
                if temperature != 0:
                    from ..tensor.random import next_key
                    key = next_key()
                else:
                    # greedy never consumes randomness — a fixed key keeps
                    # the global PRNG stream untouched so seeded runs are
                    # reproducible regardless of generation length
                    key = jax.random.PRNGKey(0)
                new, cache = loop(params, first, jnp.int32(T0), cache,
                                  key, bucket)
                pieces.append(new[:, :n])
            toks = jnp.concatenate(pieces, axis=1)
        rest = max_new_tokens - n_cached
        if rest > 0:
            return self._generate_sliding(toks, rest, temperature, top_k,
                                          top_p)
        return Tensor(toks)

    def _decode_fns(self):
        if getattr(self, '_decode_cache', None) is None:
            self._decode_cache = make_decode_fns(self.config)
        return self._decode_cache

    def _generate_loop(self, temperature, top_k, top_p):
        """Per-(sampling-config) cache of the on-device generation loop —
        repeated generate() calls with the same knobs must not retrace."""
        key = (temperature, top_k, top_p)
        cache = getattr(self, '_gen_loops', None)
        if cache is None:
            from .decode_cache import DecodeFnCache
            cache = self._gen_loops = DecodeFnCache(name='gpt.gen_loops')
        return cache.get(key, lambda: make_generate_loop(
            self.config, temperature, top_k, top_p))

    def enable_int8_decode(self, enable=True):
        """Serve ``generate`` from weight-only int8 matrices (halved HBM
        traffic on the bandwidth-bound decode path; ops/weight_only.py).
        Quantization snapshots the CURRENT weights lazily at the next
        ``generate``; call again after further training to re-snapshot.
        Training and ``forward`` are untouched."""
        self._int8_decode = enable
        self._int8_params = None
        return self

    def _decode_params(self):
        if not getattr(self, '_int8_decode', False):
            return self._params()
        if getattr(self, '_int8_params', None) is None:
            self._int8_params = jax.tree_util.tree_map(
                jnp.asarray, quantize_decode_params(self._params()))
        return self._int8_params

    def _generate_sliding(self, toks, max_new_tokens, temperature, top_k,
                          top_p=None):
        """Full-context recompute with a sliding window — the continuation
        once generation outgrows the KV cache (= max_seq_len). Every window
        is full-width here, so the jitted forward compiles once."""
        cfg = self.config
        if getattr(self, '_sliding_fwd', None) is None:
            # cached like _decode_fns: repeated boundary-crossing generate()
            # calls must not recompile the full-width forward each time
            self._sliding_fwd = jax.jit(lambda p, t: forward(p, t, cfg)[:, -1])
        fwd = self._sliding_fwd
        for _ in range(max_new_tokens):
            ctx = toks[:, -cfg.max_seq_len:]
            nxt = _sample(fwd(self._decode_params(), ctx), temperature,
                          top_k, top_p)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        return Tensor(toks)
