"""ERNIE/BERT-style masked-LM encoder for pretraining.

Reference capability: ERNIE/BERT pretrain with Fleet dp+sharding (reference
repo's fleet stack; model family from PaddleNLP ernie). TPU-first design like
models/gpt.py: stacked-block functional core under lax.scan, bf16 compute,
flash attention (bidirectional), dp/sharding via pjit.
"""
import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer, Parameter


@dataclasses.dataclass
class ErnieConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_mult: int = 4
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dtype: str = 'bfloat16'
    param_dtype: str = 'float32'
    remat: bool = True
    # pallas flash attention (bidirectional, additive key-padding mask
    # in-kernel); falls back to the XLA path off-TPU automatically
    use_flash: bool = True
    # attention dropout (train-time; in-kernel counter-hash masks — the
    # pretrain loss derives per-layer seeds from its dropout_key)
    dropout: float = 0.0

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def ffn_size(self):
        return self.hidden_size * self.ffn_mult


def init_params(config: ErnieConfig, key):
    h, f, v, L = (config.hidden_size, config.ffn_size, config.vocab_size,
                  config.num_layers)
    pdt = jnp.dtype(config.param_dtype)
    ks = jax.random.split(key, 10)
    std = 0.02

    def nrm(kk, shape, scale=std):
        return (scale * jax.random.normal(kk, shape)).astype(pdt)

    blocks = {
        'qkv_w': nrm(ks[0], (L, h, 3 * h)), 'qkv_b': jnp.zeros((L, 3 * h), pdt),
        'proj_w': nrm(ks[1], (L, h, h)), 'proj_b': jnp.zeros((L, h), pdt),
        'ln1_g': jnp.ones((L, h), pdt), 'ln1_b': jnp.zeros((L, h), pdt),
        'fc_w': nrm(ks[2], (L, h, f)), 'fc_b': jnp.zeros((L, f), pdt),
        'out_w': nrm(ks[3], (L, f, h)), 'out_b': jnp.zeros((L, h), pdt),
        'ln2_g': jnp.ones((L, h), pdt), 'ln2_b': jnp.zeros((L, h), pdt),
    }
    return {
        'wte': nrm(ks[4], (v, h)),
        'wpe': nrm(ks[5], (config.max_seq_len, h)),
        'wtype': nrm(ks[6], (config.type_vocab_size, h)),
        'emb_ln_g': jnp.ones((h,), pdt), 'emb_ln_b': jnp.zeros((h,), pdt),
        'blocks': blocks,
        'pool_w': nrm(ks[7], (h, h)), 'pool_b': jnp.zeros((h,), pdt),
        'mlm_w': nrm(ks[8], (h, h)), 'mlm_b': jnp.zeros((h,), pdt),
        'mlm_ln_g': jnp.ones((h,), pdt), 'mlm_ln_b': jnp.zeros((h,), pdt),
        'nsp_w': nrm(ks[9], (h, 2)), 'nsp_b': jnp.zeros((2,), pdt),
    }


def _ln(x, g, b, eps=1e-12):
    m = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - m), axis=-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(var + eps) * g + b


def _block(bp, x, mask_bias, config, drop_seed=None):
    cdt = jnp.dtype(config.dtype)
    B, S, h = x.shape
    nh, hd = config.num_heads, config.head_dim
    qkv = x @ bp['qkv_w'].astype(cdt) + bp['qkv_b'].astype(cdt)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, nh, hd)
    k = k.reshape(B, S, nh, hd)
    v = v.reshape(B, S, nh, hd)
    # bidirectional attention through the flash kernels (r5): the additive
    # [B,1,1,S] key-padding bias rides in-kernel (None = the kernels' mask
    # fast path); shapes/platforms the kernels decline fall back to the
    # identical-math XLA path, which samples the identical dropout mask
    from ..ops.flash_attention import _jnp_attention, flash_attention
    drop = config.dropout if drop_seed is not None else 0.0
    mask = None if mask_bias is None else mask_bias.astype(jnp.float32)
    if config.use_flash:
        a = flash_attention(q, k, v, causal=False, mask=mask,
                            dropout_rate=drop, dropout_seed=drop_seed)
    else:
        a = _jnp_attention(q, k, v, False, mask, drop_rate=drop,
                           seed=drop_seed)
    a = a.astype(cdt).reshape(B, S, h)
    x = _ln(x + a @ bp['proj_w'].astype(cdt) + bp['proj_b'].astype(cdt),
            bp['ln1_g'], bp['ln1_b']).astype(cdt)
    y = jax.nn.gelu(x @ bp['fc_w'].astype(cdt) + bp['fc_b'].astype(cdt))
    y = y @ bp['out_w'].astype(cdt) + bp['out_b'].astype(cdt)
    return _ln(x + y, bp['ln2_g'], bp['ln2_b']).astype(cdt)


def encode(params, tokens, token_type=None, attn_mask=None, config=None,
           dropout_seed=None):
    cdt = jnp.dtype(config.dtype)
    B, S = tokens.shape
    tt = token_type if token_type is not None else jnp.zeros_like(tokens)
    x = (jnp.take(params['wte'], tokens, axis=0) +
         params['wpe'][jnp.arange(S)] +
         jnp.take(params['wtype'], tt, axis=0))
    x = _ln(x, params['emb_ln_g'], params['emb_ln_b']).astype(cdt)
    if attn_mask is not None:
        bias = jnp.where(attn_mask[:, None, None, :] > 0, 0.0, -1e30).astype(cdt)
    else:
        bias = None          # unmasked: keep the kernels' no-mask fast path

    body = partial(_block, mask_bias=bias, config=config)
    if config.remat:
        # NOTE: wrapping in `lambda bp, xx: body(bp, xx)` here recursed
        # forever — the lambda closed over the NAME `body`, which this
        # assignment rebinds to the checkpointed lambda itself
        body = jax.checkpoint(body)

    if config.dropout > 0.0 and dropout_seed is not None:
        from ..ops.flash_attention import per_layer_seeds
        xs = (params['blocks'],
              per_layer_seeds(dropout_seed, config.num_layers))

        def scan_body(c, inp):
            return body(inp[0], c, drop_seed=inp[1]), None
    else:
        xs = params['blocks']

        def scan_body(c, bp):
            return body(bp, c), None
    x, _ = jax.lax.scan(scan_body, x, xs)
    return x


def pretrain_loss(params, tokens, token_type, attn_mask, mlm_labels,
                  nsp_labels, config, dropout_key=None):
    """Masked-LM + next-sentence losses (BERT pretraining objective).
    mlm_labels: -100 where not predicted. dropout_key: enables
    config.dropout attention dropout for this step."""
    seed = (jax.random.bits(dropout_key, (1,), jnp.uint32)[0]
            if config.dropout > 0.0 and dropout_key is not None else None)
    h = encode(params, tokens, token_type, attn_mask, config, seed)
    cdt = h.dtype
    # MLM head
    mh = jax.nn.gelu(h @ params['mlm_w'].astype(cdt) + params['mlm_b'].astype(cdt))
    mh = _ln(mh, params['mlm_ln_g'], params['mlm_ln_b']).astype(cdt)
    logits = mh @ params['wte'].T.astype(cdt)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    valid = mlm_labels >= 0
    ll = jnp.take_along_axis(logp, jnp.maximum(mlm_labels, 0)[..., None],
                             axis=-1)[..., 0]
    mlm_loss = -jnp.sum(jnp.where(valid, ll, 0.0)) / jnp.maximum(
        jnp.sum(valid), 1)
    # NSP head on [CLS]
    pooled = jnp.tanh(h[:, 0] @ params['pool_w'].astype(cdt) +
                      params['pool_b'].astype(cdt))
    nsp_logits = pooled @ params['nsp_w'].astype(cdt) + params['nsp_b'].astype(cdt)
    nsp_logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), axis=-1)
    nsp_loss = -jnp.mean(jnp.take_along_axis(nsp_logp, nsp_labels[:, None],
                                             axis=-1))
    return mlm_loss + nsp_loss


class ErnieModel(Layer):
    """Stateful wrapper (sequence classification-ready)."""

    def __init__(self, config: ErnieConfig = None, **kwargs):
        super().__init__()
        self.config = config or ErnieConfig(**kwargs)
        from ..tensor.random import next_key
        raw = init_params(self.config, next_key())
        leaves, treedef = jax.tree_util.tree_flatten(raw)
        self._treedef = treedef
        self._n = len(leaves)
        for i, leaf in enumerate(leaves):
            self.add_parameter(f'p{i}', Parameter(leaf))

    def _params(self):
        return jax.tree_util.tree_unflatten(
            self._treedef, [self._parameters[f'p{i}']._value
                            for i in range(self._n)])

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        from ..core.dispatch import apply_op
        cfg = self.config
        treedef = self._treedef
        plist = [self._parameters[f'p{i}'] for i in range(self._n)]
        tt = token_type_ids
        am = attention_mask

        def pure(tok, *leaves):
            params = jax.tree_util.tree_unflatten(treedef, list(leaves))
            tok = jnp.asarray(tok).astype(jnp.int32)
            ttv = (jnp.asarray(tt._value if isinstance(tt, Tensor) else tt)
                   .astype(jnp.int32) if tt is not None else None)
            amv = (jnp.asarray(am._value if isinstance(am, Tensor) else am)
                   if am is not None else None)
            h = encode(params, tok, ttv, amv, cfg)
            cdt = h.dtype
            pooled = jnp.tanh(h[:, 0] @ params['pool_w'].astype(cdt) +
                              params['pool_b'].astype(cdt))
            return h, pooled
        return apply_op(pure, input_ids, *plist)
