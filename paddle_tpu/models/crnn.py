"""CRNN text recognizer (PP-OCRv3-class capability: conv backbone + BiLSTM +
CTC head). Reference capability: PaddleOCR rec models served via Paddle
Inference. Built from paddle_tpu.nn layers; trains with nn.CTCLoss.
"""
import paddle_tpu.nn as nn
from paddle_tpu.tensor.manipulation import squeeze, transpose


class ConvBNRelu(nn.Layer):
    def __init__(self, cin, cout, k=3, s=1, p=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=s, padding=p, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.ReLU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class CRNN(nn.Layer):
    """Input: [N, 1, 32, W] grayscale strips -> logits [N, W/4, n_classes]."""

    def __init__(self, num_classes=96, hidden_size=96, in_channels=1):
        super().__init__()
        self.backbone = nn.Sequential(
            ConvBNRelu(in_channels, 32), nn.MaxPool2D(2, 2),      # 16 x W/2
            ConvBNRelu(32, 64), nn.MaxPool2D(2, 2),               # 8 x W/4
            ConvBNRelu(64, 128),
            ConvBNRelu(128, 128), nn.MaxPool2D((2, 1), (2, 1)),   # 4 x W/4
            ConvBNRelu(128, 256), nn.MaxPool2D((4, 1), (4, 1)),   # 1 x W/4
        )
        self.rnn = nn.LSTM(256, hidden_size, num_layers=2,
                           direction='bidirect')
        self.head = nn.Linear(2 * hidden_size, num_classes)

    def forward(self, x):
        feat = self.backbone(x)                       # [N, C, 1, T]
        feat = squeeze(feat, 2)                       # [N, C, T]
        feat = transpose(feat, [0, 2, 1])             # [N, T, C]
        seq, _ = self.rnn(feat)
        return self.head(seq)                         # [N, T, classes]
