"""Mixtral-style MoE causal LM with expert parallelism.

Reference capability: Fleet MoE expert-parallel via alltoall over NCCL
(python/paddle/distributed/collective.py:alltoall + incubate MoE layers).
TPU-first: experts sharded over the 'ep' mesh axis via GSPMD — the capacity-
bucketed dispatch einsums (paddle_tpu.parallel.moe) lower to all-to-all on
ICI automatically from the shardings.
"""
import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.weight_only import is_weight_only, wo_lm_head, wo_matmul, wo_take
from ..parallel.moe import moe_ffn
from .gpt import (_layer_norm, _attention, _block_qkv, _mm,
                  cached_attention, validate_gqa)


def _c(w, cdt):
    """Cast a raw weight to the compute dtype; weight-only int8 dicts pass
    through (their consumers cast in the matmul epilogue)."""
    return w if is_weight_only(w) else w.astype(cdt)


@dataclasses.dataclass
class MoEConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    n_experts: int = 8
    # GQA/MQA (0 = MHA); must divide num_heads — see gpt.GPTConfig
    num_kv_heads: int = 0
    ffn_mult: int = 4
    capacity_factor: float = 1.25
    aux_weight: float = 0.01
    max_seq_len: int = 1024
    # attention dropout (train-time; sampled IN-KERNEL via gpt._attention
    # when the step provides a key — see gpt.GPTConfig.dropout)
    dropout: float = 0.0
    dtype: str = 'bfloat16'
    param_dtype: str = 'float32'
    remat: bool = True
    use_flash: bool = True
    sp: int = 1
    mp: int = 1
    pp: int = 1
    # blockwise LM-head cross-entropy chunk (0 disables) — see gpt.GPTConfig
    xent_chunk: int = 8192
    # serving: int8 KV cache with per-row scales — see gpt.GPTConfig
    kv_cache_int8: bool = False
    # 'fp8' runs the dense attention matmuls (qkv/proj) e4m3-fwd/e5m2-bwd
    # with delayed scaling (see gpt.GPTConfig.matmul_precision); the
    # capacity-bucketed expert einsums stay in the compute dtype — their
    # dispatch/combine contractions are not plain matmuls and per-tensor
    # scales across ragged expert loads are ill-conditioned.
    matmul_precision: str = 'none'

    def __post_init__(self):
        validate_gqa(self.num_heads, self.num_kv_heads, self.mp)
        if self.matmul_precision not in ('none', 'fp8'):
            raise ValueError(
                f"matmul_precision must be 'none' or 'fp8', "
                f"got {self.matmul_precision!r}")

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self):
        return self.num_kv_heads or self.num_heads

    @property
    def ffn_size(self):
        return self.hidden_size * self.ffn_mult


def init_params(config: MoEConfig, key):
    h, f, v, L, E = (config.hidden_size, config.ffn_size, config.vocab_size,
                     config.num_layers, config.n_experts)
    pdt = jnp.dtype(config.param_dtype)
    ks = jax.random.split(key, 8)
    std = 0.02

    def nrm(kk, shape, scale=std):
        return (scale * jax.random.normal(kk, shape)).astype(pdt)

    qkv_cols = (config.num_heads + 2 * config.kv_heads) * config.head_dim
    blocks = {
        'ln1_g': jnp.ones((L, h), pdt), 'ln1_b': jnp.zeros((L, h), pdt),
        'qkv_w': nrm(ks[0], (L, h, qkv_cols)),
        'qkv_b': jnp.zeros((L, qkv_cols), pdt),
        'proj_w': nrm(ks[1], (L, h, h)), 'proj_b': jnp.zeros((L, h), pdt),
        'ln2_g': jnp.ones((L, h), pdt), 'ln2_b': jnp.zeros((L, h), pdt),
        'gate_w': nrm(ks[2], (L, h, E), 0.01),
        'w_in': nrm(ks[3], (L, E, h, f)),
        'w_out': nrm(ks[4], (L, E, f, h)),
    }
    return {'wte': nrm(ks[5], (v, h)), 'wpe': nrm(ks[6], (config.max_seq_len, h), 0.01),
            'blocks': blocks, 'lnf_g': jnp.ones((h,), pdt),
            'lnf_b': jnp.zeros((h,), pdt)}


# Logical axis names per parameter (parallel/partitioner.py): experts ride
# 'expert' -> 'ep', attention/FFN widths 'heads'/'mlp' -> 'mp', the tied
# embedding 'vocab' -> 'mp' — all from the same rules table gpt.py uses.
# 'router' (gate_w's expert dim) is deliberately unmapped: the gate is tiny
# and every rank routes locally.
LOGICAL_AXES = {
    'wte': ('vocab', 'embed'),
    'wpe': ('positions', 'embed'),
    'blocks': {
        'ln1_g': ('layers', 'embed'), 'ln1_b': ('layers', 'embed'),
        'qkv_w': ('layers', 'embed', 'heads'),
        'qkv_b': ('layers', 'heads'),
        'proj_w': ('layers', 'heads', 'embed'),
        'proj_b': ('layers', 'embed'),
        'ln2_g': ('layers', 'embed'), 'ln2_b': ('layers', 'embed'),
        'gate_w': ('layers', 'embed', 'router'),
        'w_in': ('layers', 'expert', 'embed', 'mlp'),
        'w_out': ('layers', 'expert', 'mlp', 'embed'),
    },
    'lnf_g': ('embed',), 'lnf_b': ('embed',),
}


def param_specs(config: MoEConfig):
    """Experts sharded over 'ep'; dense weights replicated (mp optional) —
    resolved from LOGICAL_AXES through the partitioner rules table."""
    from ..parallel.partitioner import Partitioner, model_rules
    return Partitioner(rules=model_rules(mp=config.mp)).tree_specs(
        LOGICAL_AXES)


def block_fn(bp, carry, config, drop_seed=None, fp8_meta=None):
    x, aux_acc = carry
    cdt = jnp.dtype(config.dtype)
    B, S, h = x.shape
    nh, hd = config.num_heads, config.head_dim
    fm = fp8_meta or {}
    y = _layer_norm(x, bp['ln1_g'], bp['ln1_b']).astype(cdt)
    q, k, v = _block_qkv(bp, y, nh, hd, cdt, config.kv_heads,
                         fp8_meta=fm.get('qkv'))
    a = _attention(q, k, v, config, drop_seed=drop_seed).reshape(B, S, h)
    x = (x + _mm(a, bp['proj_w'], cdt, fm.get('proj'))
         + bp['proj_b'].astype(cdt))
    y = _layer_norm(x, bp['ln2_g'], bp['ln2_b']).astype(cdt)
    ff, aux = moe_ffn(y, bp['gate_w'].astype(cdt),
                      _c(bp['w_in'], cdt), _c(bp['w_out'], cdt),
                      capacity_factor=config.capacity_factor)
    return (x + ff, aux_acc + aux), None


def forward_hidden(params, tokens, config, dropout_seed=None,
                   fp8_state=None):
    """-> (final hidden [B,S,H], aux load-balance loss). dropout_seed: see
    gpt.forward_hidden (per-layer mixed seeds; None = unchanged trace).
    fp8_state (init_fp8_state): per-layer qkv/proj delayed-scaling metas
    riding the scan xs — see gpt.forward_hidden."""
    cdt = jnp.dtype(config.dtype)
    B, S = tokens.shape
    x = (wo_take(params['wte'], tokens) +
         params['wpe'][jnp.arange(S)]).astype(cdt)
    body = partial(block_fn, config=config)
    if config.remat:
        body = jax.checkpoint(body)
    carry0 = (x, jnp.zeros((), jnp.float32))
    use_drop = config.dropout > 0.0 and dropout_seed is not None
    if use_drop:
        from ..ops.flash_attention import per_layer_seeds
        seeds = per_layer_seeds(dropout_seed, config.num_layers)
    if use_drop and fp8_state is not None:
        xs = (params['blocks'], seeds, fp8_state['blocks'])

        def scan_body(c, inp):
            return body(inp[0], c, drop_seed=inp[1], fp8_meta=inp[2])
    elif use_drop:
        xs = (params['blocks'], seeds)

        def scan_body(c, inp):
            return body(inp[0], c, drop_seed=inp[1])
    elif fp8_state is not None:
        xs = (params['blocks'], fp8_state['blocks'])

        def scan_body(c, inp):
            return body(inp[0], c, fp8_meta=inp[1])
    else:
        xs = params['blocks']

        def scan_body(c, bp):
            return body(bp, c)

    (x, aux), _ = jax.lax.scan(scan_body, carry0, xs)
    return _layer_norm(x, params['lnf_g'], params['lnf_b']).astype(cdt), aux


def forward(params, tokens, config, dropout_seed=None):
    x, aux = forward_hidden(params, tokens, config, dropout_seed)
    return wo_lm_head(x, params['wte'], x.dtype), aux


FP8_MATMULS = ('qkv', 'proj')


def init_fp8_state(config: 'MoEConfig'):
    """Delayed-scaling state for matmul_precision='fp8' (dense qkv/proj
    matmuls only — see MoEConfig). Same contract as gpt.init_fp8_state."""
    from ..quantization import fp8 as _fp8
    return {'blocks': {name: _fp8.init_matmul_meta(config.num_layers)
                       for name in FP8_MATMULS}}


def loss_fn(params, tokens, targets, config, dropout_key=None,
            fp8_state=None):
    seed = (jax.random.bits(dropout_key, (1,), jnp.uint32)[0]
            if config.dropout > 0.0 and dropout_key is not None else None)
    aux_scale = config.aux_weight / config.num_layers
    if (config.xent_chunk and config.mp == 1 and config.sp == 1
            and config.pp == 1
            and config.vocab_size % config.xent_chunk == 0):
        # blockwise LM-head loss (ops/xent.py): no [B,S,V] logits in HBM
        from ..ops.xent import softmax_xent_blockwise
        x, aux = forward_hidden(params, tokens, config, seed,
                                fp8_state=fp8_state)
        B, S, H = x.shape
        ce = softmax_xent_blockwise(x.reshape(B * S, H), params['wte'],
                                    targets.reshape(B * S),
                                    config.xent_chunk)
        return ce + aux_scale * aux
    x, aux = forward_hidden(params, tokens, config, seed,
                            fp8_state=fp8_state)
    logits = wo_lm_head(x, params['wte'], x.dtype)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + aux_scale * aux


# ---------------------------------------------------------------------------
# KV-cache autoregressive decoding (same design as gpt.py: static
# [L, B, S_max, H, Dh] cache, one compiled prefill + one compiled step;
# the MoE FFN routes per TOKEN. NOTE on parity: a 1-wide decode step gives
# every token full expert capacity, while a long training/prefill sequence
# COMPETES for capacity_factor-bounded slots — decode equals the full
# forward exactly whenever no token is dropped (generous capacity), and is
# otherwise slightly BETTER-routed than training saw)
# ---------------------------------------------------------------------------

def quantize_decode_params(params):
    """Weight-only int8 snapshot for serving (see gpt.quantize_decode_params
    and ops/weight_only.py): attention matrices, the per-expert FFN banks —
    the bulk of a MoE checkpoint — and the tied embedding go int8 with
    per-output-channel scales. The returned pytree drops straight into
    ``forward`` / ``generate``."""
    from ..ops.weight_only import quantize_weight
    blocks = dict(params['blocks'])
    for k, ax in (('qkv_w', 1), ('proj_w', 1), ('w_in', 2), ('w_out', 2)):
        blocks[k] = quantize_weight(blocks[k], reduce_axis=ax)
    out = dict(params)
    out['blocks'] = blocks
    out['wte'] = quantize_weight(params['wte'], reduce_axis=1)
    return out


def init_kv_cache(config: 'MoEConfig', batch):
    cdt = jnp.dtype(config.dtype)
    shape = (config.num_layers, batch, config.max_seq_len,
             config.kv_heads, config.head_dim)
    if config.kv_cache_int8:
        from ..ops.weight_only import init_kv_bank
        return {'k': init_kv_bank(shape), 'v': init_kv_bank(shape)}
    return {'k': jnp.zeros(shape, cdt), 'v': jnp.zeros(shape, cdt)}


def _cached_block(bp, x, k_cache, v_cache, pos, config, page_table=None,
                  valid=None, tail=False):
    cdt = jnp.dtype(config.dtype)
    B, T, h = x.shape
    nh, hd = config.num_heads, config.head_dim
    y = _layer_norm(x, bp['ln1_g'], bp['ln1_b']).astype(cdt)
    q, k, v = _block_qkv(bp, y, nh, hd, cdt, config.kv_heads)
    x, k_cache, v_cache = cached_attention(
        x, q, k, v, k_cache, v_cache, pos, bp['proj_w'], bp['proj_b'], cdt,
        page_table=page_table, valid=valid, tail=tail)
    y = _layer_norm(x, bp['ln2_g'], bp['ln2_b']).astype(cdt)
    ff, _ = moe_ffn(y, bp['gate_w'].astype(cdt), _c(bp['w_in'], cdt),
                    _c(bp['w_out'], cdt),
                    capacity_factor=config.capacity_factor)
    return x + ff, k_cache, v_cache


def forward_with_cache(params, tokens, cache, pos, config, last_only=False,
                       partitioner=None):
    """[B, T] tokens at absolute positions starting at ``pos`` (traced
    scalar) -> (logits, cache). See gpt.forward_with_cache. A paged cache
    (gpt.is_paged) routes through gpt.paged_forward_with_cache with THIS
    module's block body (MoE FFN per token; note the capacity caveat in
    the section comment above — decode slots in one batch compete for
    expert capacity, so exact dense parity needs generous
    capacity_factor). ``partitioner`` (mesh-bound, serving over an mp=N
    mesh) pins the paged pool to the ``kv_heads`` layout."""
    from .gpt import is_paged, paged_forward_with_cache
    if is_paged(cache):
        return paged_forward_with_cache(params, tokens, cache, pos, config,
                                        last_only=last_only,
                                        block=_cached_block,
                                        partitioner=partitioner)
    cdt = jnp.dtype(config.dtype)
    B, T = tokens.shape
    ppos = pos + jnp.arange(T)
    x = (wo_take(params['wte'], tokens)
         + jnp.take(params['wpe'], ppos, axis=0)).astype(cdt)

    def scan_body(carry, inp):
        xx = carry
        bp, kc, vc = inp
        xx, kc, vc = _cached_block(bp, xx, kc, vc, pos, config)
        return xx, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        scan_body, x, (params['blocks'], cache['k'], cache['v']))
    if last_only:
        x = x[:, -1:]
    x = _layer_norm(x, params['lnf_g'], params['lnf_b']).astype(cdt)
    return wo_lm_head(x, params['wte'], cdt), {'k': k_new, 'v': v_new}


def make_decode_fns(config):
    """-> (prefill, step) jitted with donated caches (see gpt.py)."""
    @partial(jax.jit, donate_argnums=(2,))
    def prefill(params, prompt, cache):
        logits, cache = forward_with_cache(params, prompt, cache,
                                           jnp.int32(0), config,
                                           last_only=True)
        return logits[:, -1], cache

    @partial(jax.jit, donate_argnums=(3,))
    def step(params, tok, pos, cache):
        logits, cache = forward_with_cache(params, tok[:, None], cache, pos,
                                           config)
        return logits[:, 0], cache

    return prefill, step


from .decode_cache import DecodeFnCache

_decode_fns_cache = DecodeFnCache(name='moe_gpt.decode_fns')


def _decode_fns_for(config):
    """Memoize per config (bounded LRU — see models/decode_cache.py):
    repeated generate() calls must not rebuild the jit closures (and so
    recompile prefill/step) every time, and abandoned configs must not pin
    their executables forever."""
    cfg_key = tuple(sorted(dataclasses.asdict(config).items()))
    return _decode_fns_cache.get(cfg_key, lambda: make_decode_fns(config))


def generate(params, config, prompt, max_new_tokens, temperature=0.0,
             top_k=None, key=None, *, top_p=None):
    """Functional greedy/sampled generation over the KV cache. ``prompt``:
    [B, T0] int32 with T0 < max_seq_len; generation is capped at the cache
    window (T0 + n <= max_seq_len + 1). ``key`` makes sampling
    reproducible (split per step); otherwise the global stream is used."""
    from .gpt import _sample
    B, T0 = prompt.shape
    if T0 >= config.max_seq_len:
        raise ValueError(
            f'prompt length {T0} >= max_seq_len {config.max_seq_len}: the '
            'KV cache cannot hold it — truncate the prompt or raise '
            'max_seq_len')
    n = min(max_new_tokens, config.max_seq_len - T0 + 1)
    if n < max_new_tokens:
        import warnings
        warnings.warn(
            f'generate: max_new_tokens={max_new_tokens} exceeds the KV-cache '
            f'window (max_seq_len={config.max_seq_len}, prompt={T0}); only '
            f'{n} tokens will be generated. Raise max_seq_len or use '
            'gpt.GPTForCausalLM.generate for sliding-window continuation.')
    prefill, step = _decode_fns_for(config)
    cache = init_kv_cache(config, B)
    logits, cache = prefill(params, jnp.asarray(prompt, jnp.int32), cache)
    if key is None and temperature != 0:
        # greedy never consumes randomness: the global stream must not
        # advance (seeded-script reproducibility — review r5g)
        from ..tensor.random import next_key
        key = next_key()
    if key is not None:
        key, first_key = jax.random.split(key)
    else:
        first_key = None
    first = _sample(logits, temperature, top_k, top_p, key=first_key)
    pieces = [jnp.asarray(prompt, jnp.int32), first[:, None]]
    if n > 1:
        # remaining tokens run ON DEVICE in one dispatch (r5: the per-step
        # python loop was tunnel-dispatch-bound — see gpt.make_generate_loop)
        loop = _generate_loop_for(config, temperature, top_k, top_p)
        new, _ = loop(params, first, jnp.int32(T0), cache,
                      key if key is not None else jax.random.PRNGKey(0),
                      n - 1)
        pieces.append(new)
    return jnp.concatenate(pieces, axis=1)


_GEN_LOOPS = DecodeFnCache(name='moe_gpt.gen_loops')


def _generate_loop_for(config, temperature, top_k, top_p):
    """Memoized on-device decode loop — gpt.make_generate_loop with THIS
    module's cached forward (one loop implementation for both models; a
    fresh jit wrapper per generate() call would recompile the scanned
    program every time — review r5g). Bounded LRU: see decode_cache.py."""
    import dataclasses
    from .gpt import make_generate_loop
    cache_key = (dataclasses.astuple(config), temperature, top_k, top_p)
    return _GEN_LOOPS.get(cache_key, lambda: make_generate_loop(
        config, temperature, top_k, top_p, forward_fn=forward_with_cache))


def make_train_step(config, optimizer, mesh=None):
    from ..distributed.topology import get_mesh
    mesh = mesh or get_mesh()

    if getattr(config, 'matmul_precision', 'none') == 'fp8':
        # fp8 step: delayed-scaling state (init_fp8_state) is an extra
        # donated carry; its "gradient" IS the updated state (see
        # quantization/fp8.py), so one backward pass yields both.
        def fp8_step(params, opt_state, fp8_state, key, lr, tokens, targets):
            loss, (grads, new_fp8) = jax.value_and_grad(
                lambda p, f8: loss_fn(p, tokens, targets, config,
                                      key if config.dropout > 0.0 else None,
                                      fp8_state=f8),
                argnums=(0, 1))(params, fp8_state)
            new_p, new_s = optimizer.functional_apply(params, grads,
                                                      opt_state, lr)
            return loss, new_p, new_s, new_fp8
        return jax.jit(fp8_step, donate_argnums=(0, 1, 2))

    def step(params, opt_state, key, lr, tokens, targets):
        # the step key drives attention dropout when configured
        # (config.dropout == 0 leaves the trace unchanged — see gpt)
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, targets, config,
            key if config.dropout > 0.0 else None)
        new_p, new_s = optimizer.functional_apply(params, grads, opt_state, lr)
        return loss, new_p, new_s
    return jax.jit(step, donate_argnums=(0, 1))


def place_params(params, config, mesh):
    specs = param_specs(config)

    def put(x, s):
        try:
            return jax.device_put(x, NamedSharding(mesh, s))
        except Exception:
            return x
    return jax.tree_util.tree_map(put, params, specs)
