"""SVTR-lite text recognizer (PP-OCRv3's rec architecture class).

Capability anchor: BASELINE.json names PP-OCRv3 as a serving config; its
rec model is SVTR — a single visual model that mixes local (conv) and
global (self-attention) token interactions over the image grid, CTC-decoded.
The reference repo carries the op floor (CTC loss, conv/attention layers);
this model composes paddle_tpu.nn layers the TPU-first way: static token
grids, fused QKV attention (lowering to the pallas flash kernel when shapes
allow), and a height-pooled CTC head — no recurrence, so the whole forward
is one feed-forward XLA program (vs CRNN's lax.scan BiLSTM).

Input [N, in_channels, 32, W] -> logits [N, W/4, num_classes] (CTC).
"""
import paddle_tpu.nn as nn
from paddle_tpu.tensor.manipulation import concat, reshape, transpose
from paddle_tpu.tensor.stat import mean


class _ConvBNGelu(nn.Layer):
    def __init__(self, cin, cout, k=3, s=2):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=s, padding=k // 2,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = nn.GELU()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class _MLP(nn.Layer):
    def __init__(self, d, mult=2):
        super().__init__()
        self.fc1 = nn.Linear(d, d * mult)
        self.act = nn.GELU()
        self.fc2 = nn.Linear(d * mult, d)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


class _LocalMixer(nn.Layer):
    """Conv token mixing on the [H, W] grid (SVTR local block): depthwise
    3x3 conv over the grid, channels last in/out as [N, T, D] tokens. The
    grid height is fixed by the model (img_h // 4); width derives from the
    token count, so one set of weights serves any input width."""

    def __init__(self, d, grid_h):
        super().__init__()
        self.h = grid_h
        self.conv = nn.Conv2D(d, d, 3, padding=1, groups=d)

    def forward(self, x):
        n, t = x.shape[0], x.shape[1]
        w = t // self.h
        g = transpose(reshape(x, (n, self.h, w, -1)), [0, 3, 1, 2])
        g = self.conv(g)
        return reshape(transpose(g, [0, 2, 3, 1]), (n, t, -1))


class _GlobalMixer(nn.Layer):
    """Self-attention token mixing (SVTR global block)."""

    def __init__(self, d, heads):
        super().__init__()
        self.attn = nn.MultiHeadAttention(d, heads)

    def forward(self, x):
        return self.attn(x)


class _MixBlock(nn.Layer):
    def __init__(self, d, mixer):
        super().__init__()
        self.norm1 = nn.LayerNorm(d)
        self.mixer = mixer
        self.norm2 = nn.LayerNorm(d)
        self.mlp = _MLP(d)

    def forward(self, x):
        x = x + self.mixer(self.norm1(x))
        return x + self.mlp(self.norm2(x))


class SVTRLite(nn.Layer):
    """SVTR-lite rec model: conv patch stem -> mixed local/global token
    blocks on the [8, W/4] grid -> height-pooled CTC head."""

    def __init__(self, num_classes=96, dim=96, num_heads=4, in_channels=1,
                 img_h=32):
        super().__init__()
        self.dim, self.grid_h = dim, img_h // 4
        self.stem = nn.Sequential(_ConvBNGelu(in_channels, dim // 2),
                                  _ConvBNGelu(dim // 2, dim))     # /4 x /4
        self.block1 = _MixBlock(dim, _LocalMixer(dim, self.grid_h))
        self.block2 = _MixBlock(dim, _GlobalMixer(dim, num_heads))
        self.block3 = _MixBlock(dim, _LocalMixer(dim, self.grid_h))
        self.block4 = _MixBlock(dim, _GlobalMixer(dim, num_heads))
        self.norm = nn.LayerNorm(dim)
        self.head = nn.Linear(dim, num_classes)

    def forward(self, x):
        n, _, h, w = x.shape
        feat = self.stem(x)                               # [N, D, 8, W/4]
        gh, gw = h // 4, w // 4
        tok = reshape(transpose(feat, [0, 2, 3, 1]), (n, gh * gw, self.dim))
        tok = self.block4(self.block3(self.block2(self.block1(tok))))
        tok = self.norm(tok)
        grid = reshape(tok, (n, gh, gw, self.dim))
        seq = mean(grid, axis=1)                          # [N, W/4, D]
        return self.head(seq)                             # CTC logits
