"""Flagship model families (training-scale, TPU-first functional cores)."""
from . import gpt  # noqa: F401
from . import ernie  # noqa: F401
from . import moe_gpt  # noqa: F401
from .decode_cache import DecodeFnCache, clear_decode_caches  # noqa: F401
from .crnn import CRNN  # noqa: F401
from .ppyolo_lite import PPYOLOE, PPYOLOELite  # noqa: F401
from .svtr import SVTRLite  # noqa: F401
