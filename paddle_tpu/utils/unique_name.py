"""paddle.utils.unique_name: per-prefix name generation with guard scopes.
Reference: python/paddle/fluid/unique_name.py (generate/switch/guard)."""
import contextlib

__all__ = ['generate', 'switch', 'guard']


class UniqueNameGenerator:
    def __init__(self, prefix=None):
        self.ids = {}
        self.prefix = prefix or ''

    def __call__(self, key):
        n = self.ids.get(key, 0)
        self.ids[key] = n + 1
        return '_'.join(filter(None, [self.prefix, key, str(n)]))


_generator = UniqueNameGenerator()


def generate(key):
    """'fc' -> 'fc_0', 'fc_1', ... (scoped by the active generator)."""
    return _generator(key)


def generate_with_ignorable_key(key):
    return _generator(key)


def switch(new_generator=None):
    """Replace the active generator; returns the previous one."""
    global _generator
    old = _generator
    _generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope a fresh (or given prefix's) generator; restores on exit."""
    if isinstance(new_generator, (str, bytes)):
        if isinstance(new_generator, bytes):
            new_generator = new_generator.decode()
        new_generator = UniqueNameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
