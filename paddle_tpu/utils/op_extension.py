"""First-class custom DEVICE ops (the TPU-native analogue of the reference's
C++/CUDA custom-op mechanism, python/paddle/utils/cpp_extension/
cpp_extension.py:1 + PD_BUILD_OP).

Where the reference has users compile a C++/CUDA kernel and register it with
the operator registry, the TPU compute path is XLA/Pallas — a custom op here
is a PURE jax function (jnp/lax, or a pallas kernel for hand-tiled TPU code),
optionally with a custom VJP. ``register_op`` makes it a first-class paddle
op:

 - **eager**: called with paddle Tensors it routes through the dispatch
   layer, so ``.backward()`` sees it on the tape (custom VJPs are honored —
   the tape replays through ``jax.vjp`` which respects ``jax.custom_vjp``);
 - **jit / to_static**: the same pure function traces into the compiled
   step like any built-in op;
 - **save/load**: programs containing the op serialize to StableHLO via
   ``jit.save`` — the op's lowering travels WITH the artifact, so (unlike
   the reference's .so) a loaded ``.pdexec`` needs no re-registration.

Example (fused custom op with a custom backward)::

    import paddle_tpu as paddle

    def fused_swish(x, beta):                  # pure jnp/lax/pallas body
        return x * jax.nn.sigmoid(beta * x)

    def fused_swish_fwd(x, beta):
        out = fused_swish(x, beta)
        return out, (x, beta, out)

    def fused_swish_bwd(res, g):
        x, beta, out = res
        sig = jax.nn.sigmoid(beta * x)
        dx = g * (sig + beta * out * (1 - sig))
        dbeta = (g * x * out * (1 - sig)).sum()
        return dx, dbeta

    swish = paddle.utils.cpp_extension.register_op(
        'fused_swish', fused_swish,
        vjp=(fused_swish_fwd, fused_swish_bwd))
    y = swish(paddle.to_tensor(...), paddle.to_tensor(0.5))   # eager, taped

A pallas kernel body works the same way — write the ``pl.pallas_call`` in
the forward (see paddle_tpu/ops/flash_attention.py for the house style) and
register it here.
"""
import jax

from ..core import dispatch

_REGISTRY = {}


def register_op(name, fn=None, vjp=None, nondiff_argnums=()):
    """Register ``fn(*arrays, **static) -> array(s)`` as a paddle op.

    name: registry key (also the eager op's __name__).
    fn: pure jax function (jnp/lax/pallas). May also be used as a decorator:
        ``@register_op('my_op')``.
    vjp: optional custom backward — either a ``(fwd, bwd)`` pair with
        jax.custom_vjp semantics (fwd returns ``(out, residuals)``, bwd
        returns input cotangents), or an already-built
        ``jax.custom_vjp``-wrapped callable passed as ``fn`` with vjp=None.
    nondiff_argnums: static positional args (forwarded to jax.custom_vjp).

    Returns the eager-callable op (also retrievable via ``get_op(name)``).
    The op accepts paddle Tensors or raw arrays; with Tensor inputs that
    require grad it records a tape node exactly like built-in ops.
    """
    if fn is None:                      # decorator form
        return lambda f: register_op(name, f, vjp=vjp,
                                     nondiff_argnums=nondiff_argnums)
    pure = fn
    if vjp is not None:
        fwd, bwd = vjp
        pure = jax.custom_vjp(fn, nondiff_argnums=tuple(nondiff_argnums))
        pure.defvjp(fwd, bwd)
    pure.__name__ = name
    wrapped = dispatch.op(pure)
    wrapped.__name__ = name
    _REGISTRY[name] = wrapped
    return wrapped


def get_op(name):
    """Look up a previously registered custom op by name."""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f'custom op {name!r} is not registered (known: '
            f'{sorted(_REGISTRY)}); call register_op first') from None


def registered_ops():
    return dict(_REGISTRY)
