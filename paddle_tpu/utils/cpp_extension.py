"""paddle.utils.cpp_extension: build + load C++ extensions at runtime.
Reference: python/paddle/utils/cpp_extension/ (setuptools-based custom-op
builder with JIT ``load``).

TPU-native: device compute belongs to XLA/Pallas, so C++ extensions here
are HOST-side (data pipeline / custom samplers / runtime helpers — the same
role as native/dataloader.cpp). ``load`` compiles sources with g++ into a
shared library and returns a ctypes.CDLL; no pybind11 (not in the image).
"""
import os
import subprocess
import sysconfig

from .op_extension import get_op, register_op, registered_ops  # noqa: F401

__all__ = ['load', 'CppExtension', 'get_build_directory',
           'register_op', 'get_op', 'registered_ops']

_BUILD_ROOT = os.path.expanduser('~/.cache/paddle_tpu/extensions')


def get_build_directory():
    os.makedirs(_BUILD_ROOT, exist_ok=True)
    return _BUILD_ROOT


def CppExtension(sources, *args, **kwargs):
    """setuptools.Extension for a host-side C++ op (parity shim: returns the
    kwargs needed by ``load``; use setup(ext_modules=...) flows directly
    with setuptools for packaged builds)."""
    return {'sources': sources, 'args': args, 'kwargs': kwargs}


def load(name, sources, extra_cxx_flags=None, extra_ldflags=None,
         build_directory=None, verbose=False):
    """Compile ``sources`` into ``<build_dir>/<name>.so`` (skipped when
    up-to-date) and return it loaded via ctypes."""
    import ctypes

    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    out = os.path.join(build_dir, f'{name}.so')
    srcs = [os.path.abspath(s) for s in sources]
    stale = (not os.path.exists(out) or
             any(os.path.getmtime(s) > os.path.getmtime(out) for s in srcs))
    if stale:
        cmd = (['g++', '-O2', '-shared', '-fPIC', '-std=c++17',
                '-I' + sysconfig.get_paths()['include']]
               + (extra_cxx_flags or []) + srcs + ['-o', out]
               + (extra_ldflags or ['-lpthread']))
        if verbose:
            print(' '.join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)
