"""paddle.utils parity surface.
Reference: python/paddle/utils/__init__.py (deprecated, run_check,
require_version, try_import, unique_name, download, dlpack, cpp_extension,
Profiler/ProfilerOptions/get_profiler).
"""
from . import misc  # noqa: F401
from .misc import in_dynamic_mode, enable_static, disable_static  # noqa: F401
from . import checkpoint  # noqa: F401
from . import unique_name  # noqa: F401
from . import download  # noqa: F401
from . import dlpack  # noqa: F401
from . import cpp_extension  # noqa: F401
from . import image_util  # noqa: F401

# the reference vendors the `gast` AST-portability library for its
# dy2static transformers; this stack's transformer (jit/dy2static.py)
# targets one fixed CPython, so stdlib `ast` plays that role
import ast as gast  # noqa: F401


class OpLastCheckpointChecker:
    """Reference: utils/op_version.py — queries the C++ operator registry
    for version-upgrade notes. There is no ProgramDesc op registry here
    (XLA HLO is the IR), so every query reports 'no updates', which is the
    reference's own answer for up-to-date operators."""

    def filter_updates(self, op_name, type=None, key=''):
        return []
from .deprecated import deprecated  # noqa: F401
from .install_check import run_check  # noqa: F401
from ..profiler import Profiler, ProfilerOptions, get_profiler  # noqa: F401
from . import profiler  # noqa: F401  (paddle.utils.profiler module surface)

__all__ = ['deprecated', 'run_check', 'require_version', 'try_import']


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f'{name} is required but not installed '
                          '(no-egress environment: gate this feature)') from e


def require_version(min_version, max_version=None):
    """Check the installed paddle_tpu version is within [min, max].
    Reference: fluid/framework.py require_version."""
    from .. import version as _v

    def parse(s):
        parts = str(s).split('.')
        return tuple(int(''.join(c for c in p if c.isdigit()) or 0)
                     for p in parts[:3])

    cur = parse(_v.full_version)
    if parse(min_version) > cur:
        raise Exception(
            f'paddle_tpu version {_v.full_version} < required {min_version}')
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f'paddle_tpu version {_v.full_version} > allowed {max_version}')
