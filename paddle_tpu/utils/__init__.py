from . import misc  # noqa: F401
from .misc import in_dynamic_mode, enable_static, disable_static  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError as e:
        raise ImportError(f'{name} is required but not installed '
                          '(no-egress environment: gate this feature)') from e

from . import checkpoint  # noqa: F401
