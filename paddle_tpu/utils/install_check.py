"""paddle.utils.run_check: installation + device smoke test.
Reference: python/paddle/utils/install_check.py (single- and multi-device
fluid smoke run). TPU-native: bounded backend probe (the axon tunnel can
hang rather than fail — see bench.py), one jit'd matmul+grad on the default
device, and a sharded matmul across all local devices when there are >1.
"""
import sys
import threading

__all__ = ['run_check']


def _probe_devices(timeout_s):
    """jax.devices() in a daemon thread: a dead TPU tunnel blocks forever
    inside PJRT client creation, so the probe must be abandonable."""
    result = {}

    def probe():
        try:
            import jax
            result['devices'] = jax.devices()
        except Exception as e:   # noqa: BLE001 — report any backend error
            result['error'] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        result['error'] = TimeoutError(
            f'backend did not initialize within {timeout_s}s (device '
            f'tunnel down?)')
    return result


def run_check(timeout_s=120):
    """Verify paddle_tpu works: prints a diagnosis, returns True/False."""
    print('Running verify PaddlePaddle(TPU) program ...')
    r = _probe_devices(timeout_s)
    if 'error' in r:
        print(f'PaddlePaddle(TPU) backend is NOT available: {r["error"]}',
              file=sys.stderr)
        print('Hint: check the TPU tunnel (bench.py --relay-state) or force '
              'CPU with jax.config.update("jax_platforms", "cpu").',
              file=sys.stderr)
        return False
    devs = r['devices']
    print(f'Found {len(devs)} {devs[0].platform} device(s).')

    import jax
    import jax.numpy as jnp

    def f(w, x):
        return jnp.sum(jnp.tanh(x @ w) ** 2)

    w = jnp.ones((128, 128), jnp.float32)
    x = jnp.ones((8, 128), jnp.float32)
    loss, grad = jax.jit(jax.value_and_grad(f))(w, x)
    loss.block_until_ready()
    assert grad.shape == w.shape
    print('PaddlePaddle(TPU) single-device check passed.')

    if len(devs) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        mesh = Mesh(devs, ('dp',))
        xs = jax.device_put(jnp.ones((8 * len(devs), 128)),
                            NamedSharding(mesh, P('dp', None)))
        loss = jax.jit(f)(w, xs)
        loss.block_until_ready()
        print(f'PaddlePaddle(TPU) {len(devs)}-device sharded check passed.')

    print('PaddlePaddle(TPU) is installed successfully!')
    return True
