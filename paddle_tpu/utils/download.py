"""paddle.utils.download: dataset/weights path resolution.
Reference: python/paddle/utils/download.py (get_weights_path_from_url /
get_path_from_url with md5 check + decompress).

This deployment is zero-egress by default: URLs resolve against the local
cache (``~/.cache/paddle_tpu/<basename>``) that an operator pre-populates; a
missing cache entry raises with the exact path to provision instead of
attempting a network fetch. md5 verification and tar/zip decompression
behave like the reference.

Deployments that DO allow egress install a fetch hook::

    from paddle_tpu.utils import download
    download.FETCHER = my_fetch     # callable(url, dest_path)

Fetches then run through ``fault.retry`` — exponential backoff with jitter
and a total deadline (``RETRY`` dict tunes them) — and land atomically
(tmp file + os.replace), so a crashed fetch never leaves a truncated cache
entry that later resolves as valid.
"""
import hashlib
import os
import tarfile
import zipfile

from ..fault import retry

__all__ = ['get_weights_path_from_url']

WEIGHTS_HOME = os.path.expanduser('~/.cache/paddle_tpu/weights')
DOWNLOAD_HOME = os.path.expanduser('~/.cache/paddle_tpu/downloads')

# fetch hook: None keeps the zero-egress behavior; set to callable(url, path)
FETCHER = None
# retry policy for flaky fetches (total attempts / seconds; test-tunable)
RETRY = {'retries': 4, 'backoff': 0.5, 'factor': 2.0, 'jitter': 0.5,
         'deadline': 120.0}


def _fetch(url, path):
    """FETCHER with bounded retries; atomic into the cache."""
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    tmp = f'{path}.tmp.{os.getpid()}'

    def attempt():
        FETCHER(url, tmp)
        if not os.path.exists(tmp):
            raise IOError(f'fetcher produced no file for {url!r}')

    try:
        retry(attempt, **RETRY)
        os.replace(tmp, path)
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass


def is_url(path):
    return isinstance(path, str) and path.startswith(('http://', 'https://'))


def _md5check(path, md5sum):
    if not md5sum:
        return True
    h = hashlib.md5()
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            h.update(chunk)
    return h.hexdigest() == md5sum


def _decompress(path):
    target = os.path.splitext(path)[0]
    if path.endswith(('.tar.gz', '.tgz', '.tar')):
        if not os.path.isdir(target):
            with tarfile.open(path) as tf:
                try:
                    tf.extractall(target, filter='data')
                except TypeError:   # pre-3.10.12/3.11.4: no filter kwarg
                    tf.extractall(target)
        return target
    if path.endswith('.zip'):
        if not os.path.isdir(target):
            with zipfile.ZipFile(path) as zf:
                zf.extractall(target)
        return target
    return path


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True,
                      decompress=True):
    """Resolve url -> local file; zero-egress, cache-only (see module doc)."""
    root_dir = root_dir or DOWNLOAD_HOME
    if not is_url(url):        # already a local path
        path = url
    else:
        path = os.path.join(root_dir, url.split('/')[-1])
    if not os.path.exists(path):
        if FETCHER is not None and is_url(url):
            _fetch(url, path)
        else:
            raise FileNotFoundError(
                f'{path} not found and network fetch is disabled (zero-egress '
                f'deployment). Provision the file at that path to use '
                f'{url!r}.')
    if not _md5check(path, md5sum):
        raise IOError(f'{path} md5 mismatch (expected {md5sum})')
    return _decompress(path) if decompress else path


def get_weights_path_from_url(url, md5sum=None):
    """Weights cache lookup (reference behaviour minus the fetch)."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum, decompress=False)
