"""paddle.utils.deprecated decorator.
Reference: python/paddle/utils/deprecated.py (decorator emitting
DeprecationWarning and annotating the docstring)."""
import functools
import warnings

__all__ = ['deprecated']


def deprecated(update_to='', since='', reason=''):
    """Mark an API deprecated: warns once per call site and prepends a
    deprecation note to the wrapped function's docstring."""

    def decorator(func):
        note = 'Warning: API "{}.{}" is deprecated'.format(
            func.__module__, func.__name__)
        if since:
            note += f' since {since}'
        if update_to:
            note += f', and will be removed in the future. Use "{update_to}" instead'
        if reason:
            note += f'. Reason: {reason}'
        func.__doc__ = note + '\n\n' + (func.__doc__ or '')

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(note, category=DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator
