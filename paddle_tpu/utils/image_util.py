"""paddle.utils.image_util: classic image preprocessing helpers.
Reference: python/paddle/utils/image_util.py (resize_short / crop /
flip helpers used by 1.x example pipelines). numpy/PIL implementations
with the same semantics; HWC uint8/float arrays in and out.
"""
import numpy as np

__all__ = ['resize_short', 'center_crop', 'random_crop', 'left_right_flip',
           'simple_transform']


def _to_pil(im):
    from PIL import Image
    if isinstance(im, Image.Image):
        return im
    arr = np.asarray(im)
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype('uint8')
    return Image.fromarray(arr)


def resize_short(im, size):
    """Scale so the SHORT side equals ``size`` (aspect preserved)."""
    pil = _to_pil(im)
    w, h = pil.size
    if w < h:
        nw, nh = size, max(1, round(h * size / w))
    else:
        nw, nh = max(1, round(w * size / h)), size
    return np.asarray(pil.resize((nw, nh)))


def center_crop(im, size):
    arr = np.asarray(im)
    h, w = arr.shape[:2]
    top = max((h - size) // 2, 0)
    left = max((w - size) // 2, 0)
    return arr[top:top + size, left:left + size]


def random_crop(im, size, rng=None):
    rng = rng or np.random
    arr = np.asarray(im)
    h, w = arr.shape[:2]
    top = rng.randint(0, max(h - size, 0) + 1)
    left = rng.randint(0, max(w - size, 0) + 1)
    return arr[top:top + size, left:left + size]


def left_right_flip(im):
    return np.asarray(im)[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, mean=None,
                     scale=1.0):
    """resize_short -> (random|center) crop -> maybe flip -> CHW float."""
    im = resize_short(im, resize_size)
    im = random_crop(im, crop_size) if is_train else center_crop(im, crop_size)
    if is_train and np.random.rand() < 0.5:
        im = left_right_flip(im)
    out = np.asarray(im, 'float32') * scale
    if out.ndim == 3:
        out = out.transpose(2, 0, 1)        # HWC -> CHW
    if mean is not None:
        mean = np.asarray(mean, 'float32')
        out = out - (mean.reshape(-1, 1, 1) if mean.ndim == 1 and
                     out.ndim == 3 else mean)
    return out
