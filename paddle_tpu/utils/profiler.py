"""paddle.utils.profiler — the 2.1 profiler module surface.

Reference: python/paddle/utils/profiler.py (start_profiler/stop_profiler/
reset_profiler free functions + the deprecated Profiler shim). TPU-native:
delegates to paddle_tpu.profiler's jax.profiler wrapper; traces land as
TensorBoard-compatible protobufs.
"""
import contextlib

from ..profiler import Profiler, ProfilerOptions, get_profiler  # noqa: F401

_active = None


def start_profiler(state='All', tracer_option='Default', log_dir='./profiler_log'):
    """Begin a global profiling session (reference free-function API)."""
    global _active
    if _active is None:
        _active = Profiler(log_dir=log_dir)
        _active.start()


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    global _active
    if _active is not None:
        _active.stop()
        _active = None


def reset_profiler():
    global _active
    if _active is not None:
        _active._step_times = []


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    """``with paddle.utils.profiler.profiler(...):`` context (reference
    fluid.profiler.profiler)."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def cuda_profiler(*a, **kw):  # pragma: no cover — CUDA-only in the reference
    raise RuntimeError('cuda_profiler is CUDA-specific; use '
                       'paddle.utils.profiler.profiler() (jax.profiler '
                       'traces) on TPU')
