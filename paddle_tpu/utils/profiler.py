"""paddle.utils.profiler — the 2.1 profiler module surface.

Reference: python/paddle/utils/profiler.py, which re-exports the SAME
functions as fluid.profiler. This module therefore only delegates to the
canonical implementations in paddle_tpu.profiler — no second copy of the
session state, so the utils and top-level entry points compose.
"""
import contextlib

from ..profiler import (  # noqa: F401
    Profiler, ProfilerOptions, get_profiler, start_profiler, stop_profiler)


def reset_profiler():
    """No persistent aggregate state in the jax.profiler wrapper; kept for
    API parity (reference resets the op-stat accumulators)."""


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    """``with paddle.utils.profiler.profiler(...):`` — delegates to the
    canonical context in paddle_tpu.profiler (owns exactly the session it
    starts)."""
    from .. import profiler as _p
    with _p.profiler(state=state, sorted_key=sorted_key,
                     profile_path=profile_path) as p:
        yield p


def cuda_profiler(*a, **kw):  # pragma: no cover — CUDA-only in the reference
    raise RuntimeError('cuda_profiler is CUDA-specific; use '
                       'paddle.utils.profiler.profiler() (jax.profiler '
                       'traces) on TPU')
