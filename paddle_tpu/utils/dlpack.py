"""paddle.utils.dlpack: zero-copy tensor interchange.
Reference: python/paddle/utils/dlpack.py (to_dlpack/from_dlpack).

TPU-native: jax arrays implement the standard ``__dlpack__`` protocol.
``to_dlpack`` returns a reusable carrier object exposing ``__dlpack__`` /
``__dlpack_device__`` (consumable by torch/numpy/jax ``from_dlpack``);
legacy one-shot PyCapsules from older producers are accepted by
``from_dlpack`` via a torch bridge, since jax >= 0.5 only consumes
protocol objects.
"""
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ['to_dlpack', 'from_dlpack']


class _DLPackCarrier:
    """Protocol-object view of a tensor (reusable, unlike a raw capsule)."""

    def __init__(self, value):
        self._value = value

    def __dlpack__(self, *args, **kwargs):
        return self._value.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        return self._value.__dlpack_device__()


def to_dlpack(x):
    """Tensor -> DLPack interchange object (zero-copy where possible)."""
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return _DLPackCarrier(v)


def from_dlpack(dlpack):
    """DLPack object (protocol object or legacy capsule) -> Tensor."""
    import jax
    if hasattr(dlpack, '__dlpack__'):
        return Tensor(jax.dlpack.from_dlpack(dlpack))
    # legacy PyCapsule (e.g. torch.utils.dlpack.to_dlpack output): consume
    # it through torch, whose from_dlpack still takes capsules, then hand
    # the protocol-object torch tensor to jax
    import torch.utils.dlpack as tdl
    return Tensor(jax.dlpack.from_dlpack(tdl.from_dlpack(dlpack)))
