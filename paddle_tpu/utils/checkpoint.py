"""Distributed checkpoint/resume (orbax-backed).

Reference capability: fleet checkpoint utilities + fluid io.save/load_persistables
for sharded training state. TPU-native: orbax async checkpointing is
sharding-aware — each host writes its own shards, restore re-places arrays on
the mesh. ``CheckpointManager`` adds keep-policies and auto-resume (the
elastic-recovery story together with distributed/launch.py's restart loop).
"""
import os

import jax
import numpy as np


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


class CheckpointManager:
    def __init__(self, directory, max_to_keep=3):
        ocp = _ocp()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        opts = ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                            create=True)
        self._mgr = ocp.CheckpointManager(self.directory, options=opts)

    def save(self, step, state, wait=False):
        """state: pytree of jax arrays (params/opt_state/buffers/meta)."""
        ocp = _ocp()
        self._mgr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self):
        return self._mgr.latest_step()

    def restore(self, step=None, template=None):
        ocp = _ocp()
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        if template is not None:
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(template))
        return self._mgr.restore(step)

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def save_checkpoint(path, state, step=0):
    mgr = CheckpointManager(path)
    mgr.save(step, state, wait=True)
    mgr.close()


def load_checkpoint(path, template=None):
    mgr = CheckpointManager(path)
    out = mgr.restore(template=template)
    mgr.close()
    return out


def auto_resume(path, init_fn, template=None):
    """Elastic-recovery entry: restore the newest checkpoint if one exists,
    else build fresh state with init_fn(). Returns (state, start_step)."""
    try:
        mgr = CheckpointManager(path)
        step = mgr.latest_step()
        if step is not None:
            state = mgr.restore(step, template=template)
            mgr.close()
            return state, step + 1
        mgr.close()
    except Exception:
        pass
    return init_fn(), 0
