"""Distributed checkpoint/resume with crash-safe persistence.

Reference capability: fleet checkpoint utilities + fluid io.save/load_persistables
for sharded training state. Two backends behind one manager API:

- ``local`` (default): every step is ONE atomic, manifest-verified file
  (``ckpt-<step>.pdckpt``) written through framework_io.save (tmp -> fsync
  -> os.replace + CRC32 sidecar). ``latest_step()`` only reports steps that
  pass verification, so a checkpoint truncated by a crash is never chosen
  as the resume point. Saves are retried via fault.retry.
- ``orbax``: sharding-aware async checkpointing (each host writes its own
  shards, restore re-places arrays on the mesh) for multi-host TPU jobs.

Keep policy: ``max_to_keep`` newest steps survive garbage collection; steps
divisible by ``keep_period`` are kept forever (durable milestones an
operator can always roll back to).
"""
import os
import re

import numpy as np

from .. import observability as _obs
from ..fault import CheckpointCorruptError, retry

_STEP_RE = re.compile(r'^ckpt-(\d+)\.pdckpt$')


def _step_path(directory, step):
    return os.path.join(directory, f'ckpt-{int(step)}.pdckpt')


def _verify_file(path):
    """Cheap integrity check: manifest size+CRC when a sidecar exists,
    full restricted load otherwise. -> bool."""
    from .. import framework_io as fio
    try:
        with open(path, 'rb') as f:
            data = f.read()
        m = fio._read_manifest(path)
        if m is not None:
            import zlib
            return (m.get('payload_size') == len(data)
                    and m.get('payload_crc32') == (zlib.crc32(data)
                                                   & 0xFFFFFFFF))
        fio._load_file(path)
        return True
    except Exception:
        return False


def list_steps(directory):
    """All step numbers present on disk (verified or not), ascending."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    steps = []
    for n in names:
        m = _STEP_RE.match(n)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_verified_step(directory):
    """Newest step whose checkpoint passes integrity verification, or None.
    This is the value the elastic launcher advertises through the KVStore
    so re-ranked workers agree on a restore point."""
    for step in reversed(list_steps(directory)):
        if _verify_file(_step_path(directory, step)):
            return step
    return None


def _ocp():
    import orbax.checkpoint as ocp
    return ocp


class _LocalBackend:
    def __init__(self, directory, max_to_keep, keep_period):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.keep_period = keep_period

    def save(self, step, state):
        from .. import framework_io as fio
        fio.save(state, _step_path(self.directory, step))
        self._gc()

    def _gc(self):
        steps = list_steps(self.directory)
        if self.max_to_keep is None or len(steps) <= self.max_to_keep:
            return
        drop = steps[:-self.max_to_keep] if self.max_to_keep else steps
        for s in drop:
            if self.keep_period and s % self.keep_period == 0:
                continue
            for suffix in ('', '.manifest'):
                try:
                    os.remove(_step_path(self.directory, s) + suffix)
                except OSError:
                    pass

    def latest_step(self):
        return latest_verified_step(self.directory)

    def all_steps(self):
        return list_steps(self.directory)

    def restore(self, step, template):
        from .. import framework_io as fio
        out = fio.load(_step_path(self.directory, step))
        if template is not None:
            import jax
            import jax.numpy as jnp
            out = jax.tree_util.tree_map(
                lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
                out)
        return out

    def wait(self):
        pass

    def close(self):
        pass


class _OrbaxBackend:
    def __init__(self, directory, max_to_keep, keep_period):
        ocp = _ocp()
        kw = {'max_to_keep': max_to_keep, 'create': True}
        if keep_period:
            kw['keep_period'] = keep_period
        self._mgr = ocp.CheckpointManager(directory,
                                          options=ocp.CheckpointManagerOptions(
                                              **kw))

    def save(self, step, state):
        ocp = _ocp()
        self._mgr.save(step, args=ocp.args.StandardSave(state))

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def restore(self, step, template):
        ocp = _ocp()
        if template is not None:
            return self._mgr.restore(step,
                                     args=ocp.args.StandardRestore(template))
        return self._mgr.restore(step)

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


class CheckpointManager:
    def __init__(self, directory, max_to_keep=3, keep_period=None,
                 save_retries=3, backend='local'):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.save_retries = max(1, save_retries)
        if backend == 'orbax':
            self._be = _OrbaxBackend(self.directory, max_to_keep, keep_period)
        else:
            self._be = _LocalBackend(self.directory, max_to_keep, keep_period)

    def save(self, step, state, wait=False):
        """state: pytree of arrays (params/opt_state/buffers/meta). Retried
        on transient write errors; atomic either way (a crash mid-save never
        clobbers the previous step)."""
        with _obs.span('ckpt.manager_save', step=step):
            retry(lambda: self._be.save(step, state),
                  retries=self.save_retries, backoff=0.1, jitter=0.25)
            if wait:
                self._be.wait()
        _obs.counter('ckpt.manager_saves').inc()

    def latest_step(self):
        """Newest VERIFIED step (local backend verifies CRC manifests)."""
        return self._be.latest_step()

    def all_steps(self):
        return self._be.all_steps()

    def restore(self, step=None, template=None):
        step = step if step is not None else self._be.latest_step()
        if step is None:
            return None
        with _obs.span('ckpt.restore', step=step) as sp:
            out = self._be.restore(step, template)
        _obs.counter('ckpt.restores').inc()
        # restoring after a preemption/relaunch is recovery time, not
        # training: preemption badput on the goodput ledger
        _obs.goodput.note_badput('preemption', sp.duration)
        return out

    def wait(self):
        self._be.wait()

    def close(self):
        self._be.close()


def save_checkpoint(path, state, step=0):
    mgr = CheckpointManager(path)
    mgr.save(step, state, wait=True)
    mgr.close()


def load_checkpoint(path, template=None):
    mgr = CheckpointManager(path)
    out = mgr.restore(template=template)
    mgr.close()
    return out


def auto_resume(path, init_fn, template=None):
    """Elastic-recovery entry: restore the newest INTACT checkpoint if one
    exists, else build fresh state with init_fn(). Returns
    (state, start_step). A corrupt newest checkpoint falls back to the next
    older intact one rather than failing the job."""
    try:
        mgr = CheckpointManager(path)
        for step in reversed(mgr.all_steps()):
            try:
                state = mgr.restore(step, template=template)
                mgr.close()
                return state, step + 1
            except (CheckpointCorruptError, OSError):
                continue
        mgr.close()
    except Exception:
        pass
    return init_fn(), 0
