"""Optimizers. Reference: python/paddle/optimizer/*.py."""
import jax.numpy as jnp

from .optimizer import Optimizer
from . import lr  # noqa: F401


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update(self, g, p, state, lr):
        return p - lr.astype(p.dtype) * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, p):
        return {'velocity': jnp.zeros_like(p)}

    def _update(self, g, p, state, lr):
        lr = lr.astype(p.dtype)
        v = self._momentum * state['velocity'] + g
        if self._nesterov:
            p_new = p - lr * (g + self._momentum * v)
        else:
            p_new = p - lr * v
        return p_new, {'velocity': v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon

    def init_state(self, p):
        return {'moment1': jnp.zeros_like(p), 'moment2': jnp.zeros_like(p),
                'beta1_pow': jnp.ones((), jnp.float32),
                'beta2_pow': jnp.ones((), jnp.float32)}

    def _update(self, g, p, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = state['beta1_pow'] * b1
        b2p = state['beta2_pow'] * b2
        m = b1 * state['moment1'] + (1 - b1) * g
        v = b2 * state['moment2'] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1p).astype(p.dtype)
        vhat = v / (1 - b2p).astype(p.dtype)
        p_new = p - lr.astype(p.dtype) * mhat / (jnp.sqrt(vhat) + eps)
        return p_new, {'moment1': m, 'moment2': v, 'beta1_pow': b1p,
                       'beta2_pow': b2p}


class AdamW(Adam):
    """Decoupled weight decay. Reference: python/paddle/optimizer/adamw.py."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 grad_clip=None, lr_ratio=None, apply_decay_param_fun=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, name=name)
        self._coeff = weight_decay if isinstance(weight_decay, float) else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun

    # decoupled decay is applied by the base fused step (honoring per-group
    # weight_decay overrides), not inside _update
    _decoupled = True

    def _decoupled_coeff(self, wd):
        from .optimizer import _MISSING
        if wd is _MISSING:          # group has no override: optimizer default
            return self._coeff
        if wd is None:              # explicit None: group exempt from decay
            return 0.0
        from ..regularizer import L2Decay
        if isinstance(wd, L2Decay):
            return wd._coeff
        return float(wd)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, p):
        return {'moment': jnp.zeros_like(p), 'inf_norm': jnp.zeros_like(p),
                'beta1_pow': jnp.ones((), jnp.float32)}

    def _update(self, g, p, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = state['beta1_pow'] * b1
        m = b1 * state['moment'] + (1 - b1) * g
        u = jnp.maximum(b2 * state['inf_norm'], jnp.abs(g) + eps)
        p_new = p - (lr / (1 - b1p)).astype(p.dtype) * m / u
        return p_new, {'moment': m, 'inf_norm': u, 'beta1_pow': b1p}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, p):
        return {'moment': jnp.full_like(p, self._init_acc)}

    def _update(self, g, p, state, lr):
        acc = state['moment'] + jnp.square(g)
        p_new = p - lr.astype(p.dtype) * g / (jnp.sqrt(acc) + self._eps)
        return p_new, {'moment': acc}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps, self._rho = epsilon, rho

    def init_state(self, p):
        return {'avg_squared_grad': jnp.zeros_like(p),
                'avg_squared_update': jnp.zeros_like(p)}

    def _update(self, g, p, state, lr):
        rho, eps = self._rho, self._eps
        asg = rho * state['avg_squared_grad'] + (1 - rho) * jnp.square(g)
        update = -jnp.sqrt((state['avg_squared_update'] + eps) / (asg + eps)) * g
        asu = rho * state['avg_squared_update'] + (1 - rho) * jnp.square(update)
        return p + lr.astype(p.dtype) * update, \
            {'avg_squared_grad': asg, 'avg_squared_update': asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_state(self, p):
        s = {'mean_square': jnp.zeros_like(p), 'momentum': jnp.zeros_like(p)}
        if self._centered:
            s['mean_grad'] = jnp.zeros_like(p)
        return s

    def _update(self, g, p, state, lr):
        rho, eps = self._rho, self._eps
        ms = rho * state['mean_square'] + (1 - rho) * jnp.square(g)
        if self._centered:
            mg = rho * state['mean_grad'] + (1 - rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state['momentum'] + lr.astype(p.dtype) * g / denom
        new_state = {'mean_square': ms, 'momentum': mom}
        if self._centered:
            new_state['mean_grad'] = mg
        return p - mom, new_state


class Lamb(Optimizer):
    """Layer-wise adaptive moments for large-batch training.
    Reference: python/paddle/optimizer/lamb.py."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, p):
        return {'moment1': jnp.zeros_like(p), 'moment2': jnp.zeros_like(p),
                'beta1_pow': jnp.ones((), jnp.float32),
                'beta2_pow': jnp.ones((), jnp.float32)}

    def _update(self, g, p, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        b1p = state['beta1_pow'] * b1
        b2p = state['beta2_pow'] * b2
        m = b1 * state['moment1'] + (1 - b1) * g
        v = b2 * state['moment2'] + (1 - b2) * jnp.square(g)
        mhat = m / (1 - b1p).astype(p.dtype)
        vhat = v / (1 - b2p).astype(p.dtype)
        r = mhat / (jnp.sqrt(vhat) + eps) + self._wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        p_new = p - lr.astype(p.dtype) * trust * r
        return p_new, {'moment1': m, 'moment2': v, 'beta1_pow': b1p,
                       'beta2_pow': b2p}


class LarsMomentum(Momentum):
    """LARS: layer-wise adaptive rate scaling over momentum.
    Reference: fluid/optimizer.py LarsMomentumOptimizer and the lars_momentum
    op — velocity = mu*velocity + local_lr*(g + wd*p); p -= velocity, with
    local_lr = lr * lars_coeff * ||p|| / (||g|| + wd*||p|| + eps)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=1e-9, exclude_from_weight_decay=None, name=None):
        # weight decay is applied inside the LARS update, not the base class
        super().__init__(learning_rate, momentum, parameters,
                         use_nesterov=False, weight_decay=None,
                         grad_clip=grad_clip, name=name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._lars_eps = epsilon
        self._exclude = tuple(exclude_from_weight_decay or ())

    def _update(self, g, p, state, lr):
        lr = lr.astype(jnp.float32)
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        wd = jnp.float32(self._lars_wd)
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * w_norm
            / (g_norm + wd * w_norm + self._lars_eps),
            lr)
        v = (self._momentum * state['velocity']
             + local_lr.astype(p.dtype) * (g + wd.astype(p.dtype) * p))
        return p - v, {'velocity': v}
