"""Optimizer base: dual eager/functional design.

Reference: python/paddle/optimizer/optimizer.py. Each optimizer defines a pure
``_update(g, p, state, lr, **hp) -> (new_p, new_state)`` over jax arrays.
Eager ``step()`` jit-applies it across the whole param pytree in ONE fused XLA
computation (no per-param kernel launches — the TPU analogue of the
reference's fused CUDA optimizer kernels). The same pure function powers the
functional path used by jitted train steps, fleet sharding, and hapi.
"""
import functools

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Parameter
from ..nn.clip import ClipGradBase
from . import lr as lr_mod

# Sentinel for 'key absent from the param group': distinguishes a group that
# INHERITS the optimizer-level weight decay from one that explicitly overrides
# it with None (= exempt from decay). Reference semantics: an explicit None in
# a group entry is an override, not an inherit.
_MISSING = object()


class Optimizer:
    _decoupled = False       # AdamW-style weight decay (set by subclasses)

    def _decoupled_coeff(self, wd):   # pragma: no cover — decoupled only
        raise NotImplementedError

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._grad_clip = grad_clip
        from ..regularizer import L2Decay
        if isinstance(weight_decay, float):
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        # reference parameter groups (optimizer.py docs): ``parameters`` may
        # be a list of dicts {'params': [...], 'learning_rate'/
        # 'weight_decay'/'grad_clip': override} — each group steps with its
        # own hyperparameters
        params_in = list(parameters) if parameters is not None else []
        self._param_groups = []
        if params_in and isinstance(params_in[0], dict):
            flat = []
            for g in params_in:
                gp = list(g['params'])
                flat += gp
                entry = {'params': gp}
                # key-presence semantics: an explicit 0 / 0.0 / None is an
                # OVERRIDE (e.g. exempting a group from decay), absence
                # inherits the optimizer-level setting
                if 'learning_rate' in g:
                    entry['learning_rate'] = g['learning_rate']
                if 'weight_decay' in g:
                    gwd = g['weight_decay']
                    if isinstance(gwd, (int, float)) and not isinstance(
                            gwd, bool):
                        gwd = L2Decay(float(gwd))
                    entry['weight_decay'] = gwd
                if 'grad_clip' in g:
                    entry['grad_clip'] = g['grad_clip']
                self._param_groups.append(entry)
            self._parameters = flat
        else:
            self._parameters = params_in
        self._states = {}           # id(param) -> state dict of jax arrays
        self._step_fn = None
        self._accumulated = 0
        self._lr_cache = None       # (float value, device scalar)

    # ---- hyper-params -------------------------------------------------
    def get_lr(self):
        if isinstance(self._lr, lr_mod.LRScheduler):
            return self._lr()
        return self._lr

    def set_lr(self, value):
        self._lr = value
        self._lr_cache = None

    def _lr_device(self):
        """Current LR as a cached f32 device scalar. The per-step hot loop
        feeds this straight into the compiled train step — the host->device
        upload happens only when the scheduler actually changes the value,
        not every batch. Value-keyed so LRScheduler.step()/ReduceLROnPlateau
        invalidate it without any coupling to the scheduler classes."""
        import numpy as _np
        val = float(self.get_lr())
        cache = self._lr_cache
        if cache is None or cache[0] != val:
            cache = (val, jax.device_put(_np.float32(val)))
            self._lr_cache = cache
        return cache[1]

    @property
    def _learning_rate(self):
        return self._lr

    # ---- functional core ----------------------------------------------
    def init_state(self, p):
        """state pytree (dict of arrays) for one param array."""
        return {}

    def _update(self, g, p, state, lr):
        raise NotImplementedError

    def _wd_coeff(self):
        from ..regularizer import L2Decay
        if isinstance(self._weight_decay, L2Decay):
            return self._weight_decay._coeff
        return 0.0

    def _apply_decay(self, g, p, wd=_MISSING):
        """L2 regularization folded into grad (paddle semantics: regularizer
        adds coeff*p to the gradient; AdamW instead decays weights directly).
        wd=_MISSING inherits the optimizer default; an explicit None exempts."""
        from ..regularizer import L1Decay, L2Decay
        if wd is _MISSING:
            wd = self._weight_decay
        if isinstance(wd, L2Decay):
            return g + wd._coeff * p
        if isinstance(wd, L1Decay):
            return g + wd._coeff * jnp.sign(p)
        return g

    # ---- eager step -----------------------------------------------------
    def _iter_groups(self):
        if self._param_groups:
            for i, g in enumerate(self._param_groups):
                yield i, g['params'], g
        else:
            yield 0, self._parameters, None

    def step(self):
        for gi, plist, group in self._iter_groups():
            params = [p for p in plist
                      if isinstance(p, Parameter) and p.grad is not None
                      and p.trainable]
            if not params:
                continue
            for p in params:
                if id(p) not in self._states:
                    self._states[id(p)] = self.init_state(p._value)
            grads = [p.grad._value for p in params]
            vals = [p._value for p in params]
            states = [self._states[id(p)] for p in params]
            def _of(key, default):
                return group[key] if group and key in group else default
            # Reference semantics (optimizer.py _create_param_lr): a group
            # 'learning_rate' is a SCALE of the base rate (so an LRScheduler
            # on the base still drives every group), not an absolute LR.
            scale = _of('learning_rate', 1.0)
            lr = jnp.asarray(
                self.get_lr() * (1.0 if scale is None else float(scale)),
                jnp.float32)
            clip = _of('grad_clip', self._grad_clip)
            wd = _of('weight_decay', _MISSING)

            new_vals, new_states = self._fused_apply(
                gi, clip, wd)(grads, vals, states, lr)
            for p, v, s in zip(params, new_vals, new_states):
                p._replace_value(v)
                self._states[id(p)] = s

    @functools.lru_cache(maxsize=16)
    def _fused_apply(self, _key, clip=None, wd=None):

        @jax.jit
        def apply(grads, vals, states, lr):
            if clip is not None:
                grads = clip.clip_arrays(grads)
            outs = []
            outstates = []
            for g, p, s in zip(grads, vals, states):
                if self._decoupled:
                    # AdamW-style decay: applied to the WEIGHTS before the
                    # update, honoring the per-group coefficient
                    p = p * (1 - lr.astype(p.dtype)
                             * self._decoupled_coeff(wd))
                else:
                    g = self._apply_decay(g, p, wd)
                np_, ns = self._update(g, p, s, lr)
                outs.append(np_)
                outstates.append(ns)
            return outs, outstates
        return apply

    def clear_grad(self, set_to_zero=True):
        for p in self._parameters:
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..utils import misc
        if misc.in_static_mode():
            # static-graph semantics: minimize APPENDS the backward+update
            # program (reference: backward ops on the ProgramDesc); the
            # Executor differentiates the recorded loss lineage and applies
            # this optimizer on every run() — see static.Executor._compile
            from ..static import default_main_program
            default_main_program()._opt = (self, loss)
            return None, [(p, None) for p in self._parameters]
        # Reference dygraph semantics (optimizer.py:786 in the reference):
        # backward() only COLLECTS grads already produced by loss.backward();
        # it never re-runs autograd — so `loss.backward(); opt.minimize(loss)`
        # (the AMP GradScaler flow) must not double-backward. When NO grad
        # exists at all we do run autograd (fluid static-style
        # `minimize(loss)`-only programs keep working; in that state the
        # reference would silently no-op).
        if all(p.grad is None for p in self._parameters):
            loss.backward()
        self.step()
        params_grads = [(p, p.grad) for p in self._parameters]
        self.clear_grad()
        return None, params_grads

    # ---- state dict ------------------------------------------------------
    def state_dict(self):
        out = {}
        for i, p in enumerate(self._parameters):
            st = self._states.get(id(p))
            if st:
                key = p.name or f'param_{i}'
                for k, v in st.items():
                    out[f'{key}.{k}'] = Tensor(v)
        if isinstance(self._lr, lr_mod.LRScheduler):
            out['LR_Scheduler'] = self._lr.state_dict()
        return out

    def set_state_dict(self, state):
        if 'LR_Scheduler' in state and isinstance(self._lr, lr_mod.LRScheduler):
            self._lr.set_state_dict(state['LR_Scheduler'])
        for i, p in enumerate(self._parameters):
            key = p.name or f'param_{i}'
            st = {}
            for k, v in state.items():
                if k.startswith(key + '.'):
                    st[k[len(key) + 1:]] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if st:
                self._states[id(p)] = st

    # ---- functional API for jitted train steps ---------------------------
    def functional_init(self, params):
        """params: arbitrary pytree of arrays. Returns a state pytree whose
        leaves-per-param are this optimizer's state dicts."""
        return jax.tree_util.tree_map(self.init_state, params)

    def functional_apply(self, params, grads, opt_state, lr=None):
        """Pure: returns (new_params, new_state). Usable inside jit/pjit.
        params/grads are matching pytrees; opt_state from functional_init."""
        lr = jnp.asarray(self.get_lr() if lr is None else lr, jnp.float32)
        leaves_p, treedef = jax.tree_util.tree_flatten(params)
        leaves_g = treedef.flatten_up_to(grads)
        leaves_s = treedef.flatten_up_to(opt_state)
        if self._grad_clip is not None:
            leaves_g = self._grad_clip.clip_arrays(leaves_g)
        new_p, new_s = [], []
        for p, g, s in zip(leaves_p, leaves_g, leaves_s):
            if g is None:
                new_p.append(p)
                new_s.append(s)
                continue
            g = g.astype(p.dtype)
            if self._decoupled:
                p = p * (1 - lr.astype(p.dtype)
                         * self._decoupled_coeff(_MISSING))
            else:
                g = self._apply_decay(g, p)
            np_, ns_ = self._update(g, p, s, lr)
            new_p.append(np_)
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))
