"""paddle_tpu.fault — framework-level fault tolerance primitives.

- retry():          bounded retries with backoff/jitter/deadline
- CircuitBreaker:   stop hammering a dependency that is down
- inject():         env-controlled fault points for the chaos harness
- typed errors:     CheckpointCorruptError, UnsafePayloadError, RetryError,
                    CircuitOpenError, InjectedFault

Used by framework_io (atomic verified checkpoints), utils.checkpoint
(save retries), io.DataLoader (transient __getitem__ retries + native-pool
degrade), utils.download (fetch retries), and the elastic launcher/manager
(heartbeat outage surfacing). See tools/chaos_check.py for the end-to-end
crash/resume proof.
"""
from .errors import (CheckpointCorruptError, CircuitOpenError, InjectedFault,  # noqa: F401
                     RetryError, UnsafePayloadError)
from .retry import retry  # noqa: F401
from .circuit import CLOSED, HALF_OPEN, OPEN, CircuitBreaker  # noqa: F401
from .inject import (active_points, configure, fired_count, inject,  # noqa: F401
                     reload)

__all__ = [
    'retry', 'RetryError',
    'CircuitBreaker', 'CircuitOpenError', 'CLOSED', 'OPEN', 'HALF_OPEN',
    'inject', 'configure', 'reload', 'active_points', 'fired_count',
    'InjectedFault', 'CheckpointCorruptError', 'UnsafePayloadError',
]
