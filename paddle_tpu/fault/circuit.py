"""Circuit breaker: stop hammering a dependency that is down.

closed --(failure_threshold consecutive failures)--> open
open   --(recovery_timeout elapsed)-->               half_open
half_open --success--> closed   |   --failure--> open (timer restarts)

Half-open admits at most ONE probe *in flight* at a time: when the
recovery timeout elapses, exactly one queued caller is elected to test
the dependency and every other caller keeps getting CircuitOpenError
until that probe resolves — a half-open transition must never translate
a backlog of waiting callers into a thundering herd against a replica
that is still sick. ``half_open_max_calls`` bounds how many *sequential*
trial calls one half-open period may spend before the verdict.

Every state change increments ``fault.breaker_transition{from,to}`` (per
breaker label) — the per-replica breaker-state telemetry the serving
fleet router builds its failover accounting on.

The clock is injectable so state transitions are deterministic in tests.
"""
import itertools
import threading
import time

from .. import observability as _obs
from .errors import CircuitOpenError

CLOSED = 'closed'
OPEN = 'open'
HALF_OPEN = 'half_open'

# numeric encoding for the fault.circuit_state gauge
_STATE_CODE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class CircuitBreaker:
    _seq = itertools.count()

    def __init__(self, failure_threshold=5, recovery_timeout=30.0,
                 half_open_max_calls=1, clock=None):
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_timeout = recovery_timeout
        self.half_open_max_calls = max(1, half_open_max_calls)
        self._clock = clock or time.monotonic
        self._lock = threading.RLock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = None
        self._trial_calls = 0
        self._probe_inflight = False
        self.labels = {'breaker': f'b{next(CircuitBreaker._seq)}'}
        self._publish_state()

    def _publish_state(self):
        """Mirror the current state into the fault.circuit_state gauge
        (0 closed / 1 open / 2 half_open). Looked up per call so runtime
        enable/disable of observability is honored."""
        _obs.gauge('fault.circuit_state',
                   self.labels).set(_STATE_CODE[self._state])

    def _transition(self, new_state):
        old = self._state
        self._state = new_state
        if new_state != old:
            self._publish_state()
            _obs.record_event('fault.circuit_transition',
                              frm=old, to=new_state, **self.labels)
            _obs.counter('fault.breaker_transition',
                         {'from': old, 'to': new_state,
                          **self.labels}).inc()
            if new_state == OPEN:
                _obs.counter('fault.circuit_opened').inc()

    # ---- state ----------------------------------------------------------
    @property
    def state(self):
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.recovery_timeout:
            self._transition(HALF_OPEN)
            self._trial_calls = 0
            self._probe_inflight = False

    def _open(self):
        self._opened_at = self._clock()
        self._failures = 0
        self._probe_inflight = False
        self._transition(OPEN)

    def reset(self):
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._trial_calls = 0
            self._probe_inflight = False
            self._transition(CLOSED)

    # ---- accounting -----------------------------------------------------
    def allow(self):
        """Reserve permission for one call. In half-open, exactly one probe
        may be in flight at a time (concurrent callers queued behind the
        recovery timeout must not re-hammer a sick dependency), and at most
        ``half_open_max_calls`` sequential trials run per half-open period.
        A granted half-open permit MUST be resolved with record_success()
        or record_failure() — ``call()`` does this automatically."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN:
                if self._probe_inflight:
                    return False
                if self._trial_calls < self.half_open_max_calls:
                    self._trial_calls += 1
                    self._probe_inflight = True
                    return True
                return False
            return False

    def record_success(self):
        with self._lock:
            self._probe_inflight = False
            self._failures = 0
            if self._state in (HALF_OPEN, OPEN):
                self.reset()

    def record_failure(self):
        with self._lock:
            self._probe_inflight = False
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                self._open()
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._open()

    # ---- call wrapper ---------------------------------------------------
    def call(self, fn, *args, **kwargs):
        if not self.allow():
            with self._lock:
                remaining = self.recovery_timeout - \
                    (self._clock() - self._opened_at) \
                    if self._opened_at is not None else self.recovery_timeout
            raise CircuitOpenError(max(0.0, remaining))
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result
