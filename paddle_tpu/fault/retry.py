"""Bounded retry with exponential backoff + jitter and a total deadline.

Every time source is injectable (clock/sleep/rng) so backoff schedules are
exactly reproducible under a fake clock in tests — no real sleeping, no
wall-clock flakiness.
"""
import random as _random
import time

from .. import observability as _obs
from .errors import RetryError


def retry(fn, *, retries=3, deadline=None, backoff=0.1, factor=2.0,
          max_backoff=30.0, jitter=0.0, exceptions=(Exception,),
          clock=None, sleep=None, rng=None, on_retry=None):
    """Call ``fn()`` up to ``retries`` times total.

    - ``backoff * factor**(attempt-1)`` capped at ``max_backoff`` between
      attempts; ``jitter`` stretches each delay by up to ``jitter`` fraction
      (uniform) to decorrelate a fleet retrying in lockstep.
    - ``deadline`` bounds total elapsed time (measured by ``clock``): if the
      next sleep would cross it, give up immediately.
    - only ``exceptions`` are retried; anything else propagates.
    - ``on_retry(attempt, exc, delay)`` observes each scheduled retry.

    Raises RetryError (last error chained as __cause__) when it gives up.
    """
    clock = clock or time.monotonic
    sleep = sleep or time.sleep
    _obs.counter('fault.retry_calls').inc()
    start = clock()
    attempt = 0
    while True:
        try:
            return fn()
        except exceptions as e:
            attempt += 1
            if attempt >= retries:
                _obs.counter('fault.retry_exhausted').inc()
                raise RetryError(
                    f'gave up after {attempt} attempt(s): {e!r}',
                    attempts=attempt) from e
            delay = min(backoff * (factor ** (attempt - 1)), max_backoff)
            if jitter:
                r = rng.random() if rng is not None else _random.random()
                delay *= 1.0 + jitter * r
            if deadline is not None and (clock() - start) + delay > deadline:
                _obs.counter('fault.retry_exhausted').inc()
                raise RetryError(
                    f'deadline {deadline}s exceeded after {attempt} '
                    f'attempt(s): {e!r}', attempts=attempt) from e
            _obs.counter('fault.retries').inc()
            _obs.record_event('fault.retry', attempt=attempt,
                              delay_s=round(delay, 4),
                              error=type(e).__name__)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            t0 = time.perf_counter()
            sleep(delay)
            # the backoff wait is live-but-idle wall time: requeue badput
            # on the goodput ledger. Booked as MEASURED, not scheduled, so
            # an injected fake sleep (tests) books ~nothing
            _obs.goodput.note_badput('requeue',
                                     time.perf_counter() - t0)
