"""Env-controlled fault injection — the chaos hooks behind tools/chaos_check.

Armed via ``PADDLE_FAULT_INJECT="point:prob[:action],..."`` where action is
``raise`` (default: raise InjectedFault, exercising retry/degrade paths),
``kill`` (SIGKILL the process mid-operation, exercising crash recovery), or
``delay:<secs>`` (sleep at the point then continue — a stall, not a
failure: exercises timeout/goodput-attribution paths, e.g.
``ckpt.write:1.0:delay:0.5`` injects a 500 ms checkpoint stall).
``PADDLE_FAULT_SEED`` makes firing decisions reproducible;
``PADDLE_FAULT_MAX`` caps how many faults fire per process.

Instrumented points: ``ckpt.write`` / ``ckpt.commit`` (framework_io.save,
before the payload / manifest os.replace), ``dataloader.step`` (per batch),
``collective.entry`` (all_reduce/all_gather/broadcast/barrier),
``store.heartbeat`` (elastic membership beat), ``serving.dispatch``
(serving.InferenceEngine, entry of every batched device call — inside the
engine's CircuitBreaker, so armed faults exercise the breaker-opening
path), ``warmup.cache`` (warmup.enable_persistent_cache, inside the
retried directory probe — armed faults exercise the fall-back-to-cold-
compiles path), ``fleet.route`` (serving.FleetRouter's routing decision;
an armed fault parks the request for control-loop retry rather than
losing it), ``fleet.failover`` (the fleet health sweep; an armed
fault kills one replica via ``shutdown(drain=False)``, driving the full
resubmit-without-loss failover path — the hook tools/fleet_drill.py is
built on), ``host.admit`` (serving.ModelHost admission, before any side
effect — an armed fault aborts the deploy/swap-in with accounting
unchanged), and ``host.evict`` (ModelHost eviction — an armed fault
aborts the eviction, leaving the victim live; an admission that needed
the space fails without side effects).

When no spec is armed, ``inject()`` is a single falsy-dict check — zero cost
on hot paths.
"""
import os
import random
import signal
import time

from .errors import InjectedFault

ENV_SPEC = 'PADDLE_FAULT_INJECT'
ENV_SEED = 'PADDLE_FAULT_SEED'
ENV_MAX = 'PADDLE_FAULT_MAX'

_points = {}            # point -> (probability, action, delay_s)
_rng = random.Random()
_max_faults = None
_fired = 0


def _parse(spec):
    out = {}
    for part in (spec or '').split(','):
        part = part.strip()
        if not part:
            continue
        fields = part.split(':')
        if len(fields) < 2:
            raise ValueError(
                f'bad fault spec {part!r}: want point:prob[:action]')
        point, prob = fields[0], float(fields[1])
        action = fields[2] if len(fields) > 2 else 'raise'
        delay = 0.0
        if action == 'delay':
            if len(fields) < 4:
                raise ValueError(
                    f'bad fault spec {part!r}: delay wants '
                    f'point:prob:delay:<secs>')
            delay = float(fields[3])
        elif action not in ('raise', 'kill'):
            raise ValueError(f'bad fault action {action!r} in {part!r}')
        out[point] = (prob, action, delay)
    return out


def _norm_entry(ent):
    """Accept legacy 2-tuples from programmatic configure(dict) callers."""
    if len(ent) == 2:
        return (ent[0], ent[1], 0.0)
    return ent


def configure(spec=None, seed=None, max_faults=None):
    """Programmatic arming (tests); ``configure(None)`` disarms."""
    global _points, _rng, _max_faults, _fired
    _points = _parse(spec) if isinstance(spec, str) else dict(spec or {})
    _rng = random.Random(seed)
    _max_faults = max_faults
    _fired = 0


def reload():
    """Re-read the PADDLE_FAULT_* environment (called once at import)."""
    seed = os.environ.get(ENV_SEED)
    mx = os.environ.get(ENV_MAX)
    configure(os.environ.get(ENV_SPEC),
              seed=int(seed) if seed else None,
              max_faults=int(mx) if mx else None)


def active_points():
    return dict(_points)


def fired_count():
    return _fired


def inject(point):
    """Fire the armed fault at ``point`` (probabilistically); no-op when
    disarmed. Place at the entry of any operation whose failure the caller
    claims to survive."""
    if not _points:
        return
    ent = _points.get(point)
    if ent is None:
        return
    global _fired
    if _max_faults is not None and _fired >= _max_faults:
        return
    prob, action, delay = _norm_entry(ent)
    if _rng.random() >= prob:
        return
    _fired += 1
    from .. import observability as _obs
    _obs.counter('fault.injected', {'point': point}).inc()
    _obs.record_event('fault.injected', point=point, action=action)
    if action == 'kill':
        os.kill(os.getpid(), signal.SIGKILL)
    if action == 'delay':
        time.sleep(delay)       # a stall, not a failure — then proceed
        return
    raise InjectedFault(point)


reload()
