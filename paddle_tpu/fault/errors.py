"""Typed fault-tolerance errors shared across the framework."""
import pickle


class CheckpointCorruptError(IOError):
    """A checkpoint failed integrity verification (truncated payload,
    CRC/size mismatch against its manifest, or undecodable pickle stream).
    Raised by framework_io.load instead of silently returning garbage."""

    def __init__(self, path, reason):
        super().__init__(f'corrupt checkpoint {path!r}: {reason}')
        self.path = path
        self.reason = reason


class UnsafePayloadError(pickle.UnpicklingError):
    """The pickle stream referenced a global outside the numpy/builtins
    allowlist — loading it could execute arbitrary code, so it is refused.
    Subclasses UnpicklingError so generic pickle handling still applies."""


class RetryError(RuntimeError):
    """retry() gave up: attempts exhausted or deadline exceeded. The last
    underlying exception is chained as __cause__."""

    def __init__(self, message, attempts):
        super().__init__(message)
        self.attempts = attempts

    @property
    def last_exception(self):
        return self.__cause__


class CircuitOpenError(RuntimeError):
    """A CircuitBreaker is open: calls are refused without attempting the
    underlying operation until the recovery timeout elapses."""

    def __init__(self, retry_after):
        super().__init__(f'circuit open; retry in {retry_after:.3f}s')
        self.retry_after = retry_after


class InjectedFault(RuntimeError):
    """Raised by fault.inject() at an armed fault point (action=raise)."""

    def __init__(self, point):
        super().__init__(f'injected fault at {point!r}')
        self.point = point
