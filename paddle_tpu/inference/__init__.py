"""Paddle Inference engine parity.

Reference: python/paddle/inference/ wrapping the C++ AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.cc): load __model__+params,
run optimization passes, execute. TPU-native: the saved model (jit.save) is
params + StableHLO; Predictor AOT-compiles the forward with XLA once
(Config controls precision/donation) and serves host arrays in/out. XLA's
fusion/layout passes play the role of the reference's IR passes.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np


class PrecisionType:
    Float32 = 'float32'
    Bfloat16 = 'bfloat16'
    Half = 'float16'
    Int8 = 'int8'


class PlaceType:
    CPU = 'cpu'
    TPU = 'tpu'
    GPU = 'gpu'


class Config:
    """Reference: paddle.inference.Config."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config(model_dir) or Config(prog, params)
        self.model_path = prog_file
        self.params_path = params_file
        self._precision = PrecisionType.Float32
        self._device = 'tpu'
        self._enable_memory_optim = True
        self._batch_dim_dynamic = False

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = 'gpu'

    def enable_tpu(self):
        self._device = 'tpu'

    def disable_gpu(self):
        self._device = 'cpu'

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def switch_batch_dim_dynamic(self, flag=True):
        """Serve variable batch sizes through bucketed executables: inputs
        are padded up to the next power-of-two batch and outputs sliced
        back, so N distinct request sizes cost O(log N) compiles instead of
        one executable each (see paddle_tpu.serving for the full dynamic
        batcher this feeds)."""
        self._batch_dim_dynamic = bool(flag)

    def set_precision(self, precision):
        self._precision = precision

    def enable_mkldnn(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def model_dir(self):
        return self.model_path


class Tensor_:
    """Handle for named input/output bindings."""

    def __init__(self, predictor, name, is_input):
        self._p = predictor
        self.name = name
        self._is_input = is_input

    def copy_from_cpu(self, arr):
        self._p._feed[self.name] = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass

    def copy_to_cpu(self):
        return self._p._results[self.name]


class Predictor:
    """AOT-compiled server for a jit.save'd model."""

    def __init__(self, config):
        self.config = config
        path = config.model_path
        if path.endswith('.pdmodel'):
            path = path[:-len('.pdmodel')]
        # Standalone serialized program (jax.export) written by jit.save lets
        # the Predictor serve with no Python Layer at all, the way the
        # reference's AnalysisPredictor runs the __model__ ProgramDesc.
        from ..jit import load_saved_artifacts
        self._params, self._buffers, self._meta, self._exec = \
            load_saved_artifacts(path)
        self._input_names = [f'x{i}' for i in range(
            len(self._meta.get('input_spec', [])) or 1)]
        self._feed = {}
        self._results = {}
        self._layer = None
        self._compiled = {}
        self._trace_count = 0     # trace-time side effect (tests assert
        self._output_names = ['out0']   # one compile per bucket)

    def attach_layer(self, layer):
        """Bind the Layer class instance whose forward defines the program.
        (The reference reconstructs from ProgramDesc; we re-bind the module —
        or run the saved StableHLO via compile_stablehlo when layer-free.)"""
        layer.set_state_dict({**{k: v for k, v in self._params.items()},
                              **self._buffers})
        layer.eval()
        self._layer = layer
        return self

    def get_input_names(self):
        return self._input_names

    def get_output_names(self):
        return self._output_names

    def get_input_handle(self, name):
        return Tensor_(self, name, True)

    def get_output_handle(self, name):
        return Tensor_(self, name, False)

    def _get_compiled(self, shapes_key):
        fn = self._compiled.get(shapes_key)
        if fn is None:
            from ..nn.layer_base import functional_call
            layer = self._layer
            prec = self.config._precision
            if prec == PrecisionType.Float32 and \
                    self._meta.get('precision') in (PrecisionType.Bfloat16,
                                                    PrecisionType.Half):
                # model was offline-converted (convert_to_mixed_precision):
                # honor its stored precision so inputs get lowered to match
                prec = self._meta['precision']
            params = self._params
            low = {PrecisionType.Bfloat16: jnp.bfloat16,
                   PrecisionType.Half: jnp.float16}.get(prec)
            def lower_tree(d):
                return {k: (v.astype(low)
                            if jnp.issubdtype(v.dtype, jnp.floating) else v)
                        for k, v in d.items()}
            buffers = self._buffers
            if low is not None:
                params = lower_tree(params)
                # buffers too (e.g. BN running stats): an f32 buffer would
                # re-promote activations back to f32 mid-network
                buffers = lower_tree(buffers)

            def infer(*xs):
                self._trace_count += 1
                if low is not None:
                    # inputs must match the lowered param dtype (convs and
                    # matmuls require homogeneous operand dtypes)
                    xs = [x.astype(low)
                          if jnp.issubdtype(x.dtype, jnp.floating) else x
                          for x in xs]
                # baking the frozen weights into the executable is the
                # point here: XLA constant-folds them (int8 scales,
                # lowered dtypes) and the predictor re-jits per shape
                # pt-lint: disable=trace-closure-capture
                out, _ = functional_call(layer, params, buffers, *xs)
                return out
            fn = jax.jit(infer)
            self._compiled[shapes_key] = fn
        return fn

    def run(self, inputs=None):
        if inputs is not None:
            feed = [jnp.asarray(np.asarray(x)) for x in inputs]
        else:
            feed = [jnp.asarray(self._feed[n]) for n in self._input_names]
        n_rows = None    # set when dynamic batching is on for this run
        bucket = None    # the padded size actually compiled for
        if self._layer is None:
            if self._exec is None:
                raise RuntimeError(
                    'model was saved without a standalone program (.pdexec); '
                    'call attach_layer(model) or re-export with jit.save')
            if self.config._precision != PrecisionType.Float32:
                import warnings
                warnings.warn(
                    'Config precision is ignored when serving the exported '
                    'program (dtypes are pinned at jit.save); attach_layer() '
                    'to serve at a different precision', stacklevel=2)
            if not self._meta.get('poly_batch', False):
                spec = self._meta.get('input_spec', [])
                for f, s in zip(feed, spec):
                    want = [1 if d == -1 else d for d in s['shape']]
                    if list(f.shape) != want:
                        raise ValueError(
                            f'saved program was exported with fixed input '
                            f'shape {want} (shape polymorphism unavailable '
                            f'for this model); got {list(f.shape)}. '
                            f'attach_layer(model) for dynamic shapes.')
            out = self._exec.call(self._params, self._buffers, *feed)
        else:
            if self.config._batch_dim_dynamic and feed and \
                    getattr(feed[0], 'ndim', 0) >= 1:
                # bucketed dynamic batching: pad every input whose leading
                # dim matches the batch up to the next power-of-two bucket,
                # run the per-bucket cached executable, slice outputs back.
                # N distinct request sizes -> O(log N) compiles.
                from ..serving.bucketing import bucket_for
                n_rows = feed[0].shape[0]
                bucket = bucket_for(n_rows)
                if bucket != n_rows:
                    feed = [jnp.concatenate(
                        [f, jnp.repeat(f[-1:], bucket - n_rows, axis=0)],
                        axis=0)
                        if getattr(f, 'ndim', 0) >= 1
                        and f.shape[0] == n_rows else f
                        for f in feed]
            key = tuple((tuple(f.shape), str(f.dtype)) for f in feed)
            import sys as _sys
            wm = _sys.modules.get('paddle_tpu.warmup.manifest')
            if wm is not None and wm.capturing():
                wm.record(wm.predictor_entry(
                    key, precision=str(self.config._precision)))
            fn = self._get_compiled(key)
            out = fn(*feed)
            from .. import observability as _obs
            if _obs.enabled():
                label = 'predictor.' + ';'.join(
                    'x'.join(map(str, f.shape)) or 'scalar' for f in feed)
                if _obs.perf.analyzed(label) is None:
                    # executable-cache hit (same concrete feed): publishes
                    # perf.flops{fn}/hbm_bytes{fn,kind} for this feed key
                    _obs.perf.analyze(label, fn, tuple(feed))
        outs = out if isinstance(out, (list, tuple)) else [out]
        outs = [np.asarray(o) for o in outs]
        if bucket is not None and bucket != n_rows:
            # slice ONLY outputs whose leading dim is the padded batch;
            # auxiliary outputs (e.g. a (heads, ...) attention map) whose
            # shape[0] merely differs from n_rows must pass through intact
            outs = [o[:n_rows] if (getattr(o, 'ndim', 0) >= 1
                                   and o.shape[0] == bucket) else o
                    for o in outs]
        self._output_names = [f'out{i}' for i in range(len(outs))]
        self._results = dict(zip(self._output_names, outs))
        return outs

    def warmup(self, manifest):
        """AOT-prebuild the ``run()`` feed signatures recorded in a warmup
        manifest — the modern form of Paddle's "run once with dummy data"
        Predictor warmup idiom, except no data is needed at all. Requires
        an attached Layer (exported programs have pinned shapes and nothing
        to prebuild). Returns the prebuild report."""
        if self._layer is None:
            raise RuntimeError('warmup needs an attached Layer; the exported '
                               'program is already a single executable')
        from .. import warmup as _warmup_mod
        return _warmup_mod.prebuild(manifest, predictor=self)


def create_predictor(config):
    return Predictor(config)


def convert_to_mixed_precision(model_file, params_file=None,
                               save_model_path=None, save_params_path=None,
                               mixed_precision='bfloat16', backend=None,
                               black_list=None, **kwargs):
    """Offline-convert a jit.save'd model's weights to a mixed-precision
    copy (reference: paddle.inference.convert_to_mixed_precision rewriting
    the ProgramDesc). TPU-native: floating params are cast to the target
    dtype (bf16 is the TPU-native choice) and re-saved under the new
    prefix; the serialized fp32 program is NOT carried over (its dtypes are
    pinned), so the converted model serves through attach_layer(), where
    the Predictor re-jits at the stored precision.

    ``model_file``: path to the source '.pdmodel' (or its prefix);
    ``save_model_path``: destination prefix (or '.pdmodel' path).
    """
    import json

    from ..framework_io import save as fsave
    from ..jit import load_saved_artifacts

    def _prefix(p):
        return p[:-len('.pdmodel')] if p.endswith('.pdmodel') else p

    src = _prefix(model_file)
    if save_model_path is None:
        raise ValueError('save_model_path is required')
    dst = _prefix(save_model_path)
    params, buffers, meta, _exec = load_saved_artifacts(src)
    dtype = jnp.dtype({'bfloat16': jnp.bfloat16, 'float16': jnp.float16,
                       'fp16': jnp.float16, 'bf16': jnp.bfloat16}
                      .get(str(mixed_precision), mixed_precision))
    skip = set(black_list or ())

    def cast(name, v):
        if name in skip or not jnp.issubdtype(v.dtype, jnp.floating):
            return np.asarray(v)
        return np.asarray(v.astype(dtype))

    # buffers too: f32 BN running stats would re-promote activations
    state = {'params': {k: cast(k, v) for k, v in params.items()},
             'buffers': {k: cast(k, v) for k, v in buffers.items()}}
    os.makedirs(os.path.dirname(dst) or '.', exist_ok=True)
    fsave(state, dst + '.pdparams')
    meta = dict(meta, exported=False, poly_batch=False,
                precision=str(np.dtype(dtype).name),
                converted_from=os.path.basename(src))
    with open(dst + '.pdmodel', 'w') as f:
        json.dump(meta, f)
    return dst


Tensor = Tensor_     # reference name (fluid/inference Tensor binding)


class DataType:
    """Reference: paddle_infer::DataType enum."""
    FLOAT32 = 'float32'
    FLOAT16 = 'float16'
    INT64 = 'int64'
    INT32 = 'int32'
    UINT8 = 'uint8'
    INT8 = 'int8'
    BOOL = 'bool'


_DTYPE_NBYTES = {DataType.FLOAT32: 4, DataType.FLOAT16: 2,
                 DataType.INT64: 8, DataType.INT32: 4,
                 DataType.UINT8: 1, DataType.INT8: 1, DataType.BOOL: 1}


def get_num_bytes_of_data_type(dtype):
    """Reference: paddle_infer::GetNumBytesOfDataType."""
    return _DTYPE_NBYTES.get(dtype, np.dtype(dtype).itemsize)


def get_version():
    from ..version import full_version
    return f'paddle_tpu inference {full_version} (XLA backend)'


class PredictorPool:
    """size-N pool of Predictors over one Config. The reference clones the
    AnalysisPredictor per thread; XLA executables are thread-safe, so the
    pool shares ONE compiled program and hands out independent feed/fetch
    binding contexts — same API, far less memory."""

    def __init__(self, config, size=1):
        self._main = Predictor(config)
        self._predictors = [self._main]
        for _ in range(max(0, int(size) - 1)):
            clone = Predictor.__new__(Predictor)
            clone.__dict__.update(self._main.__dict__)
            clone._feed = {}
            clone._results = {}
            self._predictors.append(clone)

    def retrive(self, idx):      # reference spells it 'retrive'
        return self._predictors[idx]

    retrieve = retrive
