"""``paddle.fluid.dygraph`` aliases -> 2.x nn/eager API.
Reference: python/paddle/fluid/dygraph/ (layers.py, base.py, nn.py)."""
import contextlib

from ..core.tensor import Tensor, no_grad_ctx as no_grad  # noqa: F401
from ..core.tensor import to_tensor as to_variable  # noqa: F401
from ..nn import (  # noqa: F401
    AvgPool2D, BatchNorm1D, BatchNorm2D, Conv2D, Dropout, Embedding,
    LayerNorm, Linear, MaxPool2D)

BatchNorm = BatchNorm2D     # 1.x name
from ..nn.layer_base import Layer  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """1.x dygraph guard — eager is the default here; pure pass-through."""
    yield


def save_dygraph(state_dict, model_path):
    from ..framework_io import save
    save(state_dict, model_path + '.pdparams')


def load_dygraph(model_path):
    from ..framework_io import load
    import os
    path = model_path if model_path.endswith('.pdparams') \
        else model_path + '.pdparams'
    state = load(path)
    return state, None       # (param_state, optimizer_state) tuple in 1.x
