"""``paddle.fluid.optimizer`` aliases (XxxOptimizer naming).
Reference: python/paddle/fluid/optimizer.py."""
from ..optimizer import (  # noqa: F401
    Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, LarsMomentum, Momentum,
    RMSProp, SGD)

SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
AdagradOptimizer = Adagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
