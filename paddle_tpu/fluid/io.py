"""``paddle.fluid.io`` aliases -> jit.save/load + io datasets.
Reference: python/paddle/fluid/io.py."""
from ..io import DataLoader  # noqa: F401


def save_inference_model(dirname, feeded_var_names=None, target_vars=None,
                         executor=None, main_program=None, **kw):
    raise NotImplementedError(
        'fluid.io.save_inference_model serialized ProgramDesc graphs; use '
        'paddle.jit.save(layer, path, input_spec=[...]) which exports the '
        'StableHLO standalone program (.pdexec) served by '
        'paddle.inference.create_predictor.')


def load_inference_model(dirname, executor=None, **kw):
    raise NotImplementedError(
        'use paddle.jit.load(path) or paddle.inference.create_predictor('
        'Config(path + ".pdmodel")).')
