"""``paddle.fluid.layers`` aliases -> 2.x functional/tensor ops.
Reference: python/paddle/fluid/layers/ (nn.py, tensor.py, control_flow.py).
Functional-style op names map one-to-one onto the maintained
``paddle_tpu.nn.functional`` / ``paddle_tpu.tensor`` implementations.
"""
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..nn import functional as F
from ..static import data  # noqa: F401
from ..tensor import (  # noqa: F401
    abs, arange, argmax, argmin, argsort, cast, clip, concat, cos, cumsum,
    exp, expand, eye, flatten, floor, full, gather, linspace, log, matmul,
    maximum, mean, minimum, ones, ones_like, pow,
    reshape, scale, shape, sin, slice, split, sqrt, square, squeeze, stack,
    sum, tanh, tile, topk, transpose, unsqueeze, where, zeros, zeros_like)

# activation / nn functional aliases
relu = F.relu
sigmoid = F.sigmoid
softmax = F.softmax
log_softmax = F.log_softmax
leaky_relu = F.leaky_relu
elu = F.elu
gelu = F.gelu
hard_sigmoid = F.hardsigmoid
softplus = F.softplus
dropout = F.dropout
cross_entropy = F.cross_entropy
one_hot = F.one_hot
embedding = F.embedding
conv2d = F.conv2d
pool2d = None  # assigned below (mode switch)
batch_norm = F.batch_norm
layer_norm = F.layer_norm
pad = F.pad
softmax_with_cross_entropy = F.softmax_with_cross_entropy
sigmoid_cross_entropy_with_logits = \
    F.binary_cross_entropy_with_logits
reduce_mean = mean
reduce_sum = sum
reduce_max = None
elementwise_add = None
elementwise_sub = None
elementwise_mul = None
elementwise_div = None


def _binary(fn):
    def op(x, y, axis=-1, act=None, name=None):
        out = fn(x, y)
        if act is not None:
            out = getattr(F, act)(out)
        return out
    return op


elementwise_add = _binary(lambda x, y: x + y)
elementwise_sub = _binary(lambda x, y: x - y)
elementwise_mul = _binary(lambda x, y: x * y)
elementwise_div = _binary(lambda x, y: x / y)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    from ..tensor import max as _max
    return _max(input, axis=dim, keepdim=keep_dim)


def pool2d(input, pool_size=2, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, name=None):
    if global_pooling:
        from ..nn.functional import adaptive_avg_pool2d, adaptive_max_pool2d
        return (adaptive_max_pool2d(input, 1) if pool_type == 'max'
                else adaptive_avg_pool2d(input, 1))
    fn = F.max_pool2d if pool_type == 'max' else F.avg_pool2d
    return fn(input, kernel_size=pool_size, stride=pool_stride,
              padding=pool_padding)


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """fluid.layers.fc: eager functional linear with on-the-fly params is a
    1.x static-graph idiom; in this stack use paddle.nn.Linear. Kept to give
    a precise migration error rather than AttributeError."""
    raise NotImplementedError(
        'fluid.layers.fc built static-graph variables; use '
        'paddle.nn.Linear(in_features, size) (see paddle 2.x migration '
        'guide) — layer objects work in both eager and to_static modes.')


def assign(input, output=None):
    t = input if isinstance(input, Tensor) else to_tensor(input)
    return Tensor(jnp.asarray(t._value))


def fill_constant(shape, dtype, value, name=None):
    from ..tensor import full as _full
    return _full(shape, value, dtype=dtype)
