"""``paddle.fluid.initializer`` aliases.
Reference: python/paddle/fluid/initializer.py."""
from ..nn.initializer import (  # noqa: F401
    Assign, Constant, KaimingNormal, KaimingUniform, Normal,
    TruncatedNormal, Uniform, XavierNormal, XavierUniform)

ConstantInitializer = Constant
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
UniformInitializer = Uniform
XavierInitializer = XavierNormal
MSRAInitializer = KaimingNormal
NumpyArrayInitializer = Assign
