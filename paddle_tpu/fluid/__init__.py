"""Legacy ``paddle.fluid`` namespace — alias shims for 1.x/2.0-era user
programs. Reference: python/paddle/fluid/__init__.py (the pre-2.0 API the
2.x surface re-exports from).

Deliberately THIN: every symbol here aliases the maintained 2.x-style
implementation elsewhere in paddle_tpu (static Program/Executor, nn layers,
functional ops). Nothing is reimplemented; fluid-only concepts with no 2.x
analogue (LoDTensor levels, DistributeTranspiler) are absent by design —
see SURVEY §2 row 21 for the scope rationale.
"""
from ..core.tensor import Tensor  # noqa: F401
from ..device import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace, TPUPlace, XPUPlace,
    is_compiled_with_cuda)
from ..framework_io import load as load_dygraph  # noqa: F401
from ..framework_io import save as save_dygraph  # noqa: F401
from ..nn.layer_base import ParamAttr  # noqa: F401
from ..static import (  # noqa: F401
    BuildStrategy, CompiledProgram, Executor, ExecutionStrategy, Program,
    Variable, data, default_main_program, default_startup_program,
    global_scope, name_scope, program_guard, scope_guard)
from ..utils.misc import (  # noqa: F401
    disable_static as disable_dygraph, enable_static as enable_dygraph,
    in_dynamic_mode as in_dygraph_mode)
from . import dygraph  # noqa: F401
from . import initializer  # noqa: F401
from . import io  # noqa: F401
from . import layers  # noqa: F401
from . import optimizer  # noqa: F401

# fluid.core compatibility alias (user code probes paddle.fluid.core.*)
from ..device import is_compiled_with_cuda as _is_cuda


class core:
    """Shim for the C++ binding module user code introspects."""
    CPUPlace = CPUPlace
    CUDAPlace = CUDAPlace

    @staticmethod
    def is_compiled_with_cuda():
        return _is_cuda()

    @staticmethod
    def get_cuda_device_count():
        return 0


class _MixedPrecisionOptimizer:
    """fluid.contrib.mixed_precision.decorate(optimizer) — the fluid-era AMP
    entry point (reference: fluid/contrib/mixed_precision/decorator.py):
    minimize() runs scaled-loss backward + unscale + inf-skip via the 2.x
    GradScaler machinery."""

    def __init__(self, optimizer, init_loss_scaling=2. ** 15,
                 use_dynamic_loss_scaling=True, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.5,
                 **kw):
        from ..amp import GradScaler
        self._inner = optimizer
        self._scaler = GradScaler(
            init_loss_scaling=init_loss_scaling,
            use_dynamic_loss_scaling=use_dynamic_loss_scaling,
            incr_every_n_steps=incr_every_n_steps,
            decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
            incr_ratio=incr_ratio, decr_ratio=decr_ratio)

    def minimize(self, loss, *a, **kw):
        scaled = self._scaler.scale(loss)
        scaled.backward()
        self._scaler.step(self._inner)
        self._inner.clear_grad()
        return None, []

    def __getattr__(self, k):
        return getattr(self._inner, k)


class _mixed_precision_ns:
    decorate = staticmethod(_MixedPrecisionOptimizer)


class _slim_ns:
    """fluid.contrib.slim.quantization — the 2.1 quantization home."""
    from .. import quantization


class contrib:
    """fluid.contrib shim: the 2.1 home of ASP sparsity (reference:
    fluid/contrib/sparsity), quantization (slim), and mixed-precision
    training."""
    from .. import sparsity
    mixed_precision = _mixed_precision_ns
    slim = _slim_ns
