"""Gradient clipping. Reference: python/paddle/nn/clip.py (fluid/clip.py)."""
import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    def clip_arrays(self, grads):
        """Pure-array version used inside jitted train steps (list of jax arrays)."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def clip_arrays(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max) for g in grads]

    def __call__(self, params_grads):
        return [(p, None if g is None else Tensor(jnp.clip(g._value, self.min, self.max)))
                for p, g in params_grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return g * scale

    def clip_arrays(self, grads):
        return [None if g is None else self._one(g) for g in grads]

    def __call__(self, params_grads):
        return [(p, None if g is None else Tensor(self._one(g._value)))
                for p, g in params_grads]


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = clip_norm

    def clip_arrays(self, grads):
        sq = [jnp.sum(jnp.square(g)) for g in grads if g is not None]
        if not sq:
            return grads
        gnorm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return [None if g is None else g * scale for g in grads]

    def __call__(self, params_grads):
        grads = [None if g is None else g._value for _, g in params_grads]
        clipped = self.clip_arrays(grads)
        return [(p, None if c is None else Tensor(c))
                for (p, _), c in zip(params_grads, clipped)]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad._value for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float('inf'):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(g), norm_type))
                              for g in grads), 1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._replace_value(p.grad._value * scale)
    return Tensor(total)
