"""Layer (module) system.

Reference: python/paddle/fluid/dygraph/layers.py (nn.Layer). TPU-native twist:
``functional_call`` temporarily rebinds Parameters/buffers to traced arrays so
any Layer can be driven by jax.jit / jax.grad / pjit as a pure function —
that is the bridge from Paddle's stateful dygraph API to XLA's functional
compilation model.
"""
from __future__ import annotations

import collections
import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, _bump_mutation_version, no_grad_ctx
from ..core import dtype as dtypes


# Bumped whenever any Layer's ``training`` flag is written. The hapi
# executor caches its train/eval mode signature (the jit-cache key) against
# this counter instead of re-walking the layer tree every batch.
_MODE_VERSION = 0


def mode_version():
    return _MODE_VERSION


class Parameter(Tensor):
    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {'learning_rate': 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True

    def __repr__(self):
        return 'Parameter containing:\n' + super().__repr__()


class ParamAttr:
    """Reference: python/paddle/fluid/param_attr.py."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        from .initializer import Initializer
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        return ParamAttr()


class Layer:
    def __init__(self, name_scope=None, dtype='float32'):
        self.training = True
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or type(self).__name__.lower()

    # -- attribute routing ----------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get('_parameters')
        subs = self.__dict__.get('_sub_layers')
        bufs = self.__dict__.get('_buffers')
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError('call super().__init__() first')
            params[name] = value
            self.__dict__.pop(name, None)
            _bump_mutation_version()   # structural change: new/replaced param
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError('call super().__init__() first')
            subs[name] = value
            self.__dict__.pop(name, None)
            _bump_mutation_version()   # structural change: new/replaced layer
        elif bufs is not None and name in bufs:
            bufs[name] = value if isinstance(value, Tensor) or value is None else Tensor(value)
            # buffer REPLACEMENT (BatchNorm running stats in eager forward)
            # swaps the Tensor object without _replace_value — bump the
            # mutation counter so a device-resident train state reconciles
            _bump_mutation_version()
        else:
            if name == 'training':
                global _MODE_VERSION
                _MODE_VERSION += 1
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ('_parameters', '_sub_layers', '_buffers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f'{type(self).__name__!r} has no attribute {name!r}')

    def __delattr__(self, name):
        for store in ('_parameters', '_sub_layers', '_buffers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                _bump_mutation_version()
                return
        object.__delattr__(self, name)

    # -- parameter/buffer creation ---------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from . import initializer as I
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        # priority (reference nn/initializer/set_global_initializer): an
        # explicit ParamAttr initializer wins, then the global initializer
        # (weight_init for weights, bias_init for biases), then the layer's
        # default, then the framework fallback
        ginit = getattr(I.set_global_initializer,
                        'bias' if is_bias else 'weight', None)
        init = attr.initializer or ginit or default_initializer or \
            (I.Constant(0.0) if is_bias else I.XavierNormal())
        value = init(shape, dtypes.convert_dtype(dtype))
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        p.optimize_attr['learning_rate'] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        _bump_mutation_version()
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        _bump_mutation_version()
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        self.__dict__.pop(name, None)
        _bump_mutation_version()

    # -- traversal --------------------------------------------------------
    def named_sublayers(self, prefix='', include_self=False, layers_set=None):
        layers_set = layers_set if layers_set is not None else set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = prefix + ('.' if prefix else '') + name
            if id(sub) not in layers_set:
                layers_set.add(id(sub))
                yield p, sub
                yield from sub.named_sublayers(prefix=p, layers_set=layers_set)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_parameters(self, prefix='', include_sublayers=True):
        seen = set()
        for lp, layer in [(prefix, self)] + (
                [(prefix + ('.' if prefix else '') + n, l)
                 for n, l in self.named_sublayers()] if include_sublayers else []):
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lp + ('.' if lp else '') + name, p)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix='', include_sublayers=True):
        seen = set()
        for lp, layer in [(prefix, self)] + (
                [(prefix + ('.' if prefix else '') + n, l)
                 for n, l in self.named_sublayers()] if include_sublayers else []):
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (lp + ('.' if lp else '') + name, b)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def children(self):
        return iter(self._sub_layers.values())

    def named_children(self):
        return iter(self._sub_layers.items())

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- modes ------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtypes.convert_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p.dtype, jnp.floating):
                    p._replace_value(p._value.astype(dt))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    # -- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=''):
        out = destination if destination is not None else collections.OrderedDict()
        for n, p in self.named_parameters(prefix=structured_name_prefix):
            out[n] = p
        for n, b in self.named_buffers(prefix=structured_name_prefix):
            layer_name = n.rsplit('.', 1)[-1]
            if layer_name not in self._non_persistable_buffer_names:
                out[n] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                arr = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                own[k]._replace_value(arr.astype(own[k].dtype))
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        h = _HookRemover(self._forward_pre_hooks, len(self._forward_pre_hooks))
        self._forward_pre_hooks[h.idx] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = _HookRemover(self._forward_post_hooks, len(self._forward_post_hooks))
        self._forward_post_hooks[h.idx] = hook
        return h

    # -- call -------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ''

    def __repr__(self):
        lines = [type(self).__name__ + '(' + self.extra_repr()]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split('\n')
            lines.append(f'  ({name}): ' + '\n  '.join(sub_repr))
        lines.append(')')
        return '\n'.join(lines)

    def full_name(self):
        return self._name_scope


class _HookRemover:
    def __init__(self, store, idx):
        self.store = store
        self.idx = idx

    def remove(self):
        self.store.pop(self.idx, None)


# -- functional bridge ----------------------------------------------------

def _live_value(t):
    from ..core.tensor import DeviceResidentRef
    v = t._value
    return v.materialize() if type(v) is DeviceResidentRef else v


def param_arrays(layer: Layer):
    """dict name -> jax array for all trainable params (insertion-ordered).

    Plain dict, NOT OrderedDict: jax registers them as different pytree
    node types, and a train step fed ``{}`` once and an OrderedDict the
    next call silently retraces."""
    return {n: _live_value(p) for n, p in layer.named_parameters()}


def buffer_arrays(layer: Layer):
    return {n: _live_value(b) for n, b in layer.named_buffers()
            if b is not None}


@contextlib.contextmanager
def _bind(layer: Layer, params=None, buffers=None):
    saved = []
    if params:
        for n, p in layer.named_parameters():
            if n in params:
                saved.append((p, p._value))
                p._value = params[n]
    # Snapshot every buffer dict slot: forward may *replace* buffer objects
    # (e.g. BatchNorm running stats), and traced values must not leak out.
    buf_saves = []
    for _, l in [('', layer)] + list(layer.named_sublayers()):
        for bn, obj in list(l._buffers.items()):
            buf_saves.append((l, bn, obj, obj._value if obj is not None else None))
    if buffers is not None:
        for n, b in layer.named_buffers():
            if n in buffers and b is not None:
                b._value = buffers[n]
    try:
        yield
    finally:
        for p, v in saved:
            p._value = v
        for l, bn, obj, val in buf_saves:
            l._buffers[bn] = obj
            if obj is not None:
                obj._value = val


def functional_call(layer: Layer, params, buffers, *args, **kwargs):
    """Run layer.forward as a pure function of (params, buffers, args).

    Returns (outputs, new_buffers). args are jax arrays or Tensors; outputs
    are unwrapped to jax arrays (pytree). Safe under jax tracing.
    """
    return functional_call_method(layer, layer, params, buffers, *args,
                                  **kwargs)


def functional_call_method(layer: Layer, fn, params, buffers, *args, **kwargs):
    """Like functional_call but invoking ``fn`` (e.g. the pre-wrap forward
    method) instead of layer.__call__ — used by jit.to_static so a wrapped
    forward does not recurse into itself."""
    targs = [Tensor(a) if not isinstance(a, Tensor) else a for a in args]
    with _bind(layer, params, buffers):
        with no_grad_ctx():
            out = fn(*targs, **kwargs)
        new_buffers = buffer_arrays(layer)
        if buffers is not None:
            # plain dict (see param_arrays): an OrderedDict is a different
            # pytree node type than the {} fed on the first call → retrace
            new_buffers = {k: v for k, v in new_buffers.items()
                           if k in buffers}
    return jax.tree_util.tree_map(
        lambda x: x._value if isinstance(x, Tensor) else x, out,
        is_leaf=lambda x: isinstance(x, Tensor)), new_buffers
