"""Weight initializers. Reference: python/paddle/nn/initializer/*."""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.random import next_key


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle uses [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.normal(next_key(), tuple(shape), dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.truncated_normal(
            next_key(), -2.0, 2.0, tuple(shape), dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, name=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(next_key(), tuple(shape), dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, name=None):
        self.fan_in, self.fan_out = fan_in, fan_out

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(next_key(), tuple(shape), dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), dtype,
                                  minval=-limit, maxval=limit)


class Bilinear(Initializer):
    """Bilinear-interpolation kernel for transposed-conv upsampling
    (reference: fluid/initializer.py BilinearInitializer — every
    (out_c, in_c) spatial slice gets the same (K, K) interpolation
    kernel; pair with lr=0 so upsampling coefficients stay fixed)."""

    def __call__(self, shape, dtype=jnp.float32):
        shape = tuple(int(d) for d in shape)
        if len(shape) != 4:
            raise ValueError('Bilinear initializer needs a 4-D conv '
                             f'kernel shape, got {shape}')
        if shape[2] != shape[3]:
            raise ValueError('Bilinear initializer needs square kernels '
                             f'(shape[2] == shape[3]), got {shape}')
        size = shape[3]
        f = math.ceil(size / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        k = 1 - np.abs(np.arange(size) / f - c)
        filt = np.outer(k, k).astype('float32')
        return jnp.broadcast_to(jnp.asarray(filt, dtype), shape)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        from ..core.tensor import Tensor
        v = self.value._value if isinstance(self.value, Tensor) else jnp.asarray(np.asarray(self.value))
        return jnp.reshape(v.astype(dtype), tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.orthogonal(self.gain)(next_key(), tuple(shape), dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.delta_orthogonal()(next_key(), tuple(shape), dtype)


def set_global_initializer(weight_init, bias_init=None):
    """Reference nn/initializer/set_global_initializer: applies to every
    subsequently-created parameter unless a ParamAttr names its own
    initializer; set_global_initializer(None) restores the defaults.
    Consumed by Layer.create_parameter."""
    set_global_initializer.weight = weight_init
    set_global_initializer.bias = bias_init
