"""Common layers: Linear, Embedding, Dropout, Flatten, Pad, Upsample, etc.

Reference: python/paddle/nn/layer/common.py.
"""
import jax.numpy as jnp

from .layer_base import Layer, ParamAttr
from . import functional as F
from . import initializer as I


class Linear(Layer):
    """y = xW + b with W: [in_features, out_features] (paddle layout — feeds
    the MXU directly as a [*, in] @ [in, out] GEMM)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter((out_features,), bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f'in_features={self.in_features}, out_features={self.out_features}'


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        if padding_idx is not None:
            v = self.weight._value.at[padding_idx].set(0)
            self.weight._replace_value(v)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode='upscale_in_train', name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format='NCHW', name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format='NCDHW', name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode='nearest',
                 align_corners=False, align_mode=0, data_format='NCHW', name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW', name=None):
        super().__init__(size, scale_factor, 'nearest', data_format=data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW', name=None):
        super().__init__(size, scale_factor, 'bilinear', True, data_format=data_format)


class Pad1D(Layer):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCL', name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Pad1D):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCHW', name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(Pad1D):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCDHW', name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format='NCHW', name=None):
        super().__init__()
        self.padding, self.data_format = padding, data_format

    def forward(self, x):
        return F.zeropad2d(x, self.padding, self.data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        from ..core.dispatch import apply_op
        return apply_op(
            lambda a, b: jnp.power(
                jnp.sum(jnp.power(jnp.abs(a - b) + self.epsilon, self.p), -1,
                        keepdims=self.keepdim), 1.0 / self.p), x, y)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format='NCHW', name=None):
        super().__init__()
        self.upscale_factor, self.data_format = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), weight_attr)
        self.bias = self.create_parameter((out_features,), bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x
