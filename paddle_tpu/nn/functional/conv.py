"""Convolutions via lax.conv_general_dilated (XLA maps these onto the MXU).

Reference: python/paddle/nn/functional/conv.py. Weight layout follows paddle:
[out_c, in_c/groups, *spatial]. data_format 'NCHW' (paddle default) or 'NHWC'
(TPU-preferred) both lower natively — XLA picks the layout.
"""
import jax
import jax.numpy as jnp

from ...core.dispatch import op


def _tuplize(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


def _padding(padding, n, strides=None):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # nested like [[0,0],[0,0],[1,1],[1,1]]
    return [tuple(p) for p in padding[-n:]]


def _dn(ndim, data_format):
    if ndim == 1:
        return ('NCH', 'OIH', 'NCH') if data_format in ('NCL', 'NCHW') else ('NHC', 'OIH', 'NHC')
    if ndim == 2:
        return ('NCHW', 'OIHW', 'NCHW') if data_format == 'NCHW' else ('NHWC', 'OIHW', 'NHWC')
    return ('NCDHW', 'OIDHW', 'NCDHW') if data_format == 'NCDHW' else ('NDHWC', 'OIDHW', 'NDHWC')


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, nd):
    stride = _tuplize(stride, nd)
    dilation = _tuplize(dilation, nd)
    pad = _padding(padding, nd)
    dn = _dn(nd, data_format)
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=pad,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=dn,
        preferred_element_type=x.dtype if x.dtype == jnp.bfloat16 else None)
    if bias is not None:
        if dn[2].endswith('C'):
            out = out + jnp.reshape(bias, (1,) * (out.ndim - 1) + (-1,))
        else:
            out = out + jnp.reshape(bias, (1, -1) + (1,) * nd)
    return out


@op
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCL', name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 1)


@op
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCHW', name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


@op
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCDHW', name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, data_format, nd, output_size=None):
    stride = _tuplize(stride, nd)
    dilation = _tuplize(dilation, nd)
    dn = _dn(nd, data_format)
    pad = _padding(padding, nd)
    if isinstance(pad, str):
        pad_cfg = pad
    else:
        # transpose conv: effective padding = k - 1 - p (per side), via lax
        ks = weight.shape[2:]
        pad_cfg = [(dilation[i] * (ks[i] - 1) - pad[i][0],
                    dilation[i] * (ks[i] - 1) - pad[i][1]) for i in range(nd)]
    opad = _tuplize(output_padding, nd) if output_padding else (0,) * nd
    if not isinstance(pad_cfg, str):
        pad_cfg = [(p[0], p[1] + opad[i]) for i, p in enumerate(pad_cfg)]
    # weight layout [in, out/groups, *k] for paddle transpose conv
    w = jnp.swapaxes(weight, 0, 1)          # -> [out/groups, in, *k]
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    if groups > 1:
        # grouped transpose: block-diagonal trick
        in_c = weight.shape[0]
        og = w.shape[0]
        w = jnp.reshape(w, (groups, og, in_c // groups) + w.shape[2:])
        outs = []
        xs = jnp.split(x, groups, axis=1 if dn[0][1] == 'C' else -1)
        for g in range(groups):
            outs.append(jax.lax.conv_general_dilated(
                xs[g], w[g], window_strides=(1,) * nd, padding=pad_cfg,
                lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn))
        out = jnp.concatenate(outs, axis=1 if dn[2][1] == 'C' else -1)
    else:
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=(1,) * nd, padding=pad_cfg,
            lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn)
    if bias is not None:
        if dn[2].endswith('C'):
            out = out + jnp.reshape(bias, (1,) * (out.ndim - 1) + (-1,))
        else:
            out = out + jnp.reshape(bias, (1, -1) + (1,) * nd)
    return out


@op
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format='NCL',
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 1, output_size)


@op
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format='NCHW',
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2, output_size)


@op
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format='NCDHW',
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3, output_size)
