"""Common functional ops: linear, dropout, padding, interpolate, embedding,
one_hot, cosine_similarity, pixel_shuffle, unfold.

Reference: python/paddle/nn/functional/common.py, input.py, vision.py.
"""
import functools

import jax
import jax.numpy as jnp

from ...core.dispatch import op, apply_op
from ...core.tensor import Tensor
from ...tensor.random import next_key


@op
def linear(x, weight, bias=None, name=None):
    # paddle stores weight as [in, out]
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def dropout(x, p=0.5, axis=None, training=True, mode='upscale_in_train', name=None):
    if not training or p == 0:
        return x if isinstance(x, Tensor) else Tensor(x)
    key = next_key()

    def pure(v):
        shape = list(v.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == 'upscale_in_train':
            return jnp.where(keep, v / (1.0 - p), 0)
        return jnp.where(keep, v, 0)
    return apply_op(pure, x)


def dropout2d(x, p=0.5, training=True, data_format='NCHW', name=None):
    axis = [0, 1] if data_format == 'NCHW' else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format='NCDHW', name=None):
    axis = [0, 1] if data_format == 'NCDHW' else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    key = next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def pure(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / (scale * ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5))
        b = -a * alpha_p * p
        return a * jnp.where(keep, v, alpha_p) + b
    return apply_op(pure, x)


@op
def pad(x, pad, mode='constant', value=0.0, data_format='NCHW', name=None):
    pad = list(pad)
    nd = x.ndim
    if len(pad) == nd * 2:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle order: last-dim pairs first for NCHW-style formats
        n_spatial = len(pad) // 2
        cfg = [(0, 0)] * nd
        if data_format.startswith('NC'):
            dims = list(range(nd - n_spatial, nd))
        else:
            dims = list(range(1, 1 + n_spatial))
        # paddle pads are [left, right, top, bottom,...] innermost-first
        for i, d in enumerate(reversed(dims)):
            cfg[d] = (pad[2 * i], pad[2 * i + 1])
    if mode == 'constant':
        return jnp.pad(x, cfg, mode='constant', constant_values=value)
    jmode = {'reflect': 'reflect', 'replicate': 'edge', 'circular': 'wrap'}[mode]
    return jnp.pad(x, cfg, mode=jmode)


@op
def zeropad2d(x, padding, data_format='NCHW', name=None):
    l, r, t, b = padding
    cfg = [(0, 0), (0, 0), (t, b), (l, r)] if data_format == 'NCHW' else \
          [(0, 0), (t, b), (l, r), (0, 0)]
    return jnp.pad(x, cfg)


@op
def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    idx = jnp.asarray(x).astype(jnp.int32)
    out = jnp.take(weight, idx, axis=0)
    if padding_idx is not None:
        mask = (idx == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


@op
def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(jnp.asarray(x).astype(jnp.int32), num_classes)


@op
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * jnp.asarray(prior_dist)
    return (1 - epsilon) * label + epsilon / k


@op
def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


@op
def pixel_shuffle(x, upscale_factor, data_format='NCHW', name=None):
    r = upscale_factor
    if data_format == 'NCHW':
        n, c, h, w = x.shape
        x = jnp.reshape(x, (n, c // (r * r), r, r, h, w))
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return jnp.reshape(x, (n, c // (r * r), h * r, w * r))
    n, h, w, c = x.shape
    x = jnp.reshape(x, (n, h, w, r, r, c // (r * r)))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return jnp.reshape(x, (n, h * r, w * r, c // (r * r)))


@op
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
    oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), 'VALID', rhs_dilation=(dh, dw),
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    return jnp.reshape(patches, (n, c * kh * kw, oh * ow))


@functools.lru_cache(maxsize=256)
def _nearest_index(n_in, n_out):
    """Gather indices for the reference nearest rule src = floor(i*in/out)
    (jax.image's half-pixel rounding picks different pixels when
    DOWNSAMPLING). Plain trace-time numpy — NOT a dispatched op."""
    import numpy as np
    return jnp.asarray(np.minimum(
        (np.arange(n_out) * n_in / n_out).astype(np.int64), n_in - 1))


@functools.lru_cache(maxsize=64)
def _area_weights(n_in, n_out):
    """[n_out, n_in] f32 matrix of adaptive-avg-pool bins — integer
    [floor(i*in/out), ceil((i+1)*in/out)) spans averaged UNWEIGHTED
    (reference 'area' semantics). Bins vary in width, so a matrix is the
    natural form; area sizes are small in practice."""
    import numpy as np
    W = np.zeros((n_out, n_in), np.float64)
    for o in range(n_out):
        a = int(np.floor(o * n_in / n_out))
        b = int(np.ceil((o + 1) * n_in / n_out))
        W[o, a:b] = 1.0 / (b - a)
    return jnp.asarray(W, jnp.float32)


@functools.lru_cache(maxsize=256)
def _resize_taps(n_in, n_out, align_corners, align_mode, kind):
    """(indices, weights) tap lists, each [n_out], for gather + weighted
    sum (O(taps) per output — a dense matrix wastes O(n_in/taps)x FLOPs
    and pins big device arrays in the cache; review r4b).

    Source coords per the alignment rule: align_corners=True ->
    i*(in-1)/(out-1); align_mode=1 (the PaddleDetection convention) ->
    i*in/out; else half-pixel. Edge-replicated taps; cubic uses the
    reference convention a=-0.75 (jax.image uses -0.5, which is why
    resize couldn't serve bicubic)."""
    import numpy as np
    i = np.arange(n_out, dtype=np.float64)
    if align_corners:
        src = i * ((n_in - 1) / (n_out - 1)) if n_out > 1 else np.zeros(1)
    elif align_mode == 1:
        src = i * (n_in / n_out)
    else:
        src = (i + 0.5) * (n_in / n_out) - 0.5
    s0 = np.floor(src).astype(np.int64)
    frac = src - s0
    if kind == 'linear':
        taps = ((0, 1.0 - frac), (1, frac))
    else:
        a = -0.75

        def cub(t):
            t = np.abs(t)
            return np.where(
                t <= 1, ((a + 2) * t - (a + 3)) * t * t + 1,
                np.where(t < 2, a * (((t - 5) * t + 8) * t - 4), 0.0))
        taps = tuple((k, cub(frac - k)) for k in (-1, 0, 1, 2))
    idxs = tuple(jnp.asarray(np.clip(s0 + k, 0, n_in - 1)) for k, _ in taps)
    wts = tuple(jnp.asarray(w, jnp.float32) for _, w in taps)
    return idxs, wts


def interpolate(x, size=None, scale_factor=None, mode='nearest',
                align_corners=False, align_mode=0, data_format='NCHW', name=None):
    if data_format in ('NCHW', 'NCW', 'NCDHW'):
        spatial = list(x.shape[2:])
        chan_first = True
    else:
        spatial = list(x.shape[1:-1])
        chan_first = False
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = size.tolist()
        size = [int(s.item() if isinstance(s, Tensor) else s) for s in size]
    if chan_first:
        out_shape = tuple(x.shape[:2]) + tuple(size)
    else:
        out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    linear_family = mode in ('linear', 'bilinear', 'trilinear')
    if linear_family and not align_corners and align_mode == 0:
        # jax.image.resize IS the reference semantics here (half-pixel
        # centers) — verified element-exact. Through apply_op: resize's
        # internal jit rejects Tensor wrappers at abstractification.
        # antialias=False: the reference samples pointwise at half-pixel
        # coords even when downsampling (jax antialiases by default)
        return apply_op(
            lambda v: jax.image.resize(v, out_shape, method='linear',
                                       antialias=False), x)
    # nearest (reference floor rule — jax rounds from half-pixel centers,
    # differing on downsample), align_corners=True, align_mode=1 (src =
    # i*in/out — the PaddleDetection convention), bicubic (reference cubic
    # kernel a=-0.75, not jax.image's a=-0.5), and area (adaptive average
    # pooling semantics) go through exact per-axis tap gathers / bin
    # matrices (sizes are static).
    kind = {'nearest': 'nearest', 'linear': 'linear', 'bilinear': 'linear',
            'trilinear': 'linear', 'bicubic': 'cubic', 'area': 'area'}[mode]
    amode = align_mode if (kind == 'linear' and not align_corners) else 0
    first_spatial = 2 if chan_first else 1

    def pure(v):
        out = v
        for ax_i, (n_in, n_out) in enumerate(zip(spatial, size)):
            axis = first_spatial + ax_i
            if n_in == n_out:
                continue
            if kind == 'nearest':
                # gather: O(n_out) and dtype-preserving (int label maps)
                out = jnp.take(out, _nearest_index(n_in, n_out), axis=axis)
                continue
            if kind == 'area':
                w = _area_weights(n_in, n_out)
                out = jnp.moveaxis(
                    jnp.tensordot(w, jnp.moveaxis(out, axis, 0).astype(
                        jnp.float32), axes=1), 0, axis)
                continue
            idxs, wts = _resize_taps(n_in, n_out, align_corners, amode, kind)
            moved = jnp.moveaxis(out, axis, 0).astype(jnp.float32)
            bshape = (n_out,) + (1,) * (moved.ndim - 1)
            acc = sum(w.reshape(bshape) * jnp.take(moved, ix, axis=0)
                      for ix, w in zip(idxs, wts))
            out = jnp.moveaxis(acc, 0, axis)
        # weighted kinds compute in f32; hand back the input dtype so AMP
        # models don't silently upcast (and mode choice never changes the
        # output dtype)
        return out.astype(v.dtype)
    return apply_op(pure, x)


def upsample(x, size=None, scale_factor=None, mode='nearest',
             align_corners=False, align_mode=0, data_format='NCHW', name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


@op
def affine_grid(theta, out_shape, align_corners=True, name=None):
    n, c, h, w = [int(s) for s in out_shape]
    if align_corners:
        ys = jnp.linspace(-1, 1, h, dtype=theta.dtype)
        xs = jnp.linspace(-1, 1, w, dtype=theta.dtype)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1
        xs = (jnp.arange(w) * 2 + 1) / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)          # [h, w, 3]
    return jnp.einsum('hwk,nik->nhwi', base, theta)


@op
def grid_sample(x, grid, mode='bilinear', padding_mode='zeros',
                align_corners=True, name=None):
    n, c, h, w = x.shape
    gx, gy = grid[..., 0], grid[..., 1]
    if align_corners:
        ix = (gx + 1) / 2 * (w - 1)
        iy = (gy + 1) / 2 * (h - 1)
    else:
        ix = ((gx + 1) * w - 1) / 2
        iy = ((gy + 1) * h - 1) / 2
    ix0 = jnp.floor(ix)
    iy0 = jnp.floor(iy)
    ix1, iy1 = ix0 + 1, iy0 + 1

    def sample(iy_, ix_):
        iyc = jnp.clip(iy_, 0, h - 1).astype(jnp.int32)
        ixc = jnp.clip(ix_, 0, w - 1).astype(jnp.int32)
        v = x[:, :, iyc, ixc] if False else jnp.take_along_axis(
            jnp.reshape(x, (n, c, h * w)),
            jnp.reshape(iyc * w + ixc, (n, 1, -1)).astype(jnp.int32), axis=2)
        v = jnp.reshape(v, (n, c) + iy_.shape[1:])
        if padding_mode == 'zeros':
            valid = ((iy_ >= 0) & (iy_ <= h - 1) & (ix_ >= 0) & (ix_ <= w - 1))
            v = v * valid[:, None].astype(v.dtype)
        return v

    w00 = (iy1 - iy) * (ix1 - ix)
    w01 = (iy1 - iy) * (ix - ix0)
    w10 = (iy - iy0) * (ix1 - ix)
    w11 = (iy - iy0) * (ix - ix0)
    if mode == 'nearest':
        return sample(jnp.round(iy), jnp.round(ix))
    out = (sample(iy0, ix0) * w00[:, None] + sample(iy0, ix1) * w01[:, None] +
           sample(iy1, ix0) * w10[:, None] + sample(iy1, ix1) * w11[:, None])
    return out


@op
def bilinear(x1, x2, weight, bias=None, name=None):
    out = jnp.einsum('bi,oij,bj->bo', x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@op
def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x = jnp.reshape(x, (n, seg_num, c, h, w))
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x[:, 1:, :fold], jnp.zeros_like(x[:, :1, :fold])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(x[:, :1, fold:2 * fold]),
                             x[:, :-1, fold:2 * fold]], axis=1)
    mid = x[:, :, 2 * fold:]
    return jnp.reshape(jnp.concatenate([left, right, mid], axis=2), (nt, c, h, w))


@op
def npair_loss_dummy(x):
    return x
