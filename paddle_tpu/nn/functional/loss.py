"""Loss functionals. Reference: python/paddle/nn/functional/loss.py."""
import jax
import jax.numpy as jnp

from ...core.dispatch import op, apply_op
from ...core.tensor import Tensor


def _reduce(out, reduction):
    if reduction == 'mean':
        return jnp.mean(out)
    if reduction == 'sum':
        return jnp.sum(out)
    return out


@op
def cross_entropy(input, label, weight=None, ignore_index=-100, reduction='mean',
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    if use_softmax:
        logp = jax.nn.log_softmax(input, axis=axis)
    else:
        logp = jnp.log(jnp.maximum(input, 1e-30))
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
    else:
        lbl = jnp.asarray(label).astype(jnp.int32)
        if lbl.ndim == logp.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(lbl % logp.shape[axis], axis),
                                     axis=axis)
        loss = -jnp.squeeze(picked, axis=axis)
        valid = (lbl != ignore_index)
        loss = jnp.where(valid, loss, 0.0)
        if weight is not None:
            w = jnp.take(jnp.asarray(weight), lbl % logp.shape[axis], axis=0)
            w = jnp.where(valid, w, 0.0)
            loss = loss * w
            if reduction == 'mean':
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-9)
        if reduction == 'mean':
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce(loss, reduction)


@op
def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = jnp.asarray(label).astype(jnp.int32)
        squeeze = lbl.ndim == logp.ndim
        if squeeze:
            lbl_s = jnp.squeeze(lbl, axis=axis)
        else:
            lbl_s = lbl
        picked = jnp.take_along_axis(logp, jnp.expand_dims(lbl_s % logits.shape[axis], axis), axis=axis)
        loss = -picked
    if return_softmax:
        return loss, jax.nn.softmax(logits, axis=axis)
    return loss


@op
def mse_loss(input, label, reduction='mean', name=None):
    return _reduce(jnp.square(input - label), reduction)


@op
def l1_loss(input, label, reduction='mean', name=None):
    return _reduce(jnp.abs(input - label), reduction)


@op
def smooth_l1_loss(input, label, reduction='mean', delta=1.0, name=None):
    d = input - label
    loss = jnp.where(jnp.abs(d) < delta, 0.5 * d * d, delta * (jnp.abs(d) - 0.5 * delta))
    return _reduce(loss, reduction)


@op
def nll_loss(input, label, weight=None, ignore_index=-100, reduction='mean', name=None):
    lbl = jnp.asarray(label).astype(jnp.int32)
    picked = jnp.take_along_axis(input, lbl[:, None] % input.shape[1], axis=1)[:, 0]
    loss = -picked
    valid = (lbl != ignore_index)
    if weight is not None:
        w = jnp.take(jnp.asarray(weight), lbl % input.shape[1], axis=0)
        loss = loss * w
        if reduction == 'mean':
            return jnp.sum(jnp.where(valid, loss, 0)) / jnp.maximum(
                jnp.sum(jnp.where(valid, w, 0)), 1e-9)
    loss = jnp.where(valid, loss, 0.0)
    return _reduce(loss, reduction)


@op
def binary_cross_entropy(input, label, weight=None, reduction='mean', name=None):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps)) +
             (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op
def binary_cross_entropy_with_logits(logit, label, weight=None, reduction='mean',
                                     pos_weight=None, name=None):
    max_val = jnp.maximum(-logit, 0)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + max_val + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op
def kl_div(input, label, reduction='mean', name=None):
    loss = label * (jnp.log(jnp.maximum(label, 1e-30)) - input)
    if reduction == 'batchmean':
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@op
def margin_ranking_loss(input, other, label, margin=0.0, reduction='mean', name=None):
    loss = jnp.maximum(-label * (input - other) + margin, 0)
    return _reduce(loss, reduction)


@op
def hinge_embedding_loss(input, label, margin=1.0, reduction='mean', name=None):
    loss = jnp.where(label == 1, input, jnp.maximum(margin - input, 0))
    return _reduce(loss, reduction)


@op
def cosine_embedding_loss(input1, input2, label, margin=0, reduction='mean', name=None):
    cos = jnp.sum(input1 * input2, -1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1), 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(cos - margin, 0))
    return _reduce(loss, reduction)


@op
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2, epsilon=1e-6,
                        swap=False, reduction='mean', name=None):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), -1), 1 / p)
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    return _reduce(jnp.maximum(d_pos - d_neg + margin, 0), reduction)


@op
def square_error_cost(input, label):
    return jnp.square(input - label)


@op
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction='sum', name=None):
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * jnp.power(1 - p_t, gamma)
    if alpha >= 0:
        loss = loss * (alpha * label + (1 - alpha) * (1 - label))
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@op
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    sim = jnp.matmul(anchor, positive.T)
    n = anchor.shape[0]
    lbl = jnp.reshape(jnp.asarray(labels), (-1, 1))
    tgt = (lbl == lbl.T).astype(anchor.dtype)
    tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(tgt * logp, axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), 1)) +
                    jnp.mean(jnp.sum(jnp.square(positive), 1))) * 0.25
    return ce + reg


@op
def ctc_loss_fn(log_probs, labels, input_lengths, label_lengths, blank=0):
    """CTC forward (log-alpha recursion) via lax.scan.
    log_probs: [T, B, C] log-softmax scores."""
    T, B, C = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1
    lab = jnp.asarray(labels).astype(jnp.int32)
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    neg_inf = jnp.asarray(-1e30, log_probs.dtype)

    emit0 = jnp.take_along_axis(log_probs[0], ext, axis=1)       # [B,S]
    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(emit0[:, 0])
    alpha0 = alpha0.at[:, 1].set(emit0[:, 1])

    same = jnp.concatenate([jnp.zeros((B, 2), jnp.bool_),
                            ext[:, 2:] == ext[:, :-2]], axis=1)

    def step(alpha, lp_t):
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        a0 = alpha
        a1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
        a2 = jnp.where(same, neg_inf, a2)
        new = jnp.logaddexp(jnp.logaddexp(a0, a1), a2) + emit
        return new, new

    _, alphas = jax.lax.scan(step, alpha0, log_probs[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)      # [T,B,S]
    t_idx = jnp.asarray(input_lengths).astype(jnp.int32) - 1
    a_final = jnp.take_along_axis(
        alphas, t_idx[None, :, None].repeat(S, axis=2), axis=0)[0]  # [B,S]
    s_last = 2 * jnp.asarray(label_lengths).astype(jnp.int32)
    ll_blank = jnp.take_along_axis(a_final, s_last[:, None], axis=1)[:, 0]
    ll_label = jnp.take_along_axis(a_final, jnp.maximum(s_last - 1, 0)[:, None], axis=1)[:, 0]
    return -jnp.logaddexp(ll_blank, ll_label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction='mean'):
    from .activation import log_softmax
    lp = log_softmax(log_probs, axis=-1)
    loss = ctc_loss_fn(lp, labels, input_lengths, label_lengths, blank=blank)
    if reduction == 'mean':
        ll = label_lengths._value if isinstance(label_lengths, Tensor) else jnp.asarray(label_lengths)
        return apply_op(lambda l: jnp.mean(l / jnp.maximum(ll.astype(l.dtype), 1)), loss)
    if reduction == 'sum':
        return apply_op(lambda l: jnp.sum(l), loss)
    return loss


@op
def dice_loss(input, label, epsilon=1e-05, name=None):
    lbl = jax.nn.one_hot(jnp.squeeze(jnp.asarray(label), -1).astype(jnp.int32),
                         input.shape[-1], dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = 2 * jnp.sum(input * lbl, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(lbl, axis=reduce_dims)
    return jnp.mean(1 - (inter + epsilon) / (union + epsilon))


@op
def log_loss(input, label, epsilon=1e-4, name=None):
    return -label * jnp.log(input + epsilon) - (1 - label) * jnp.log(1 - input + epsilon)
