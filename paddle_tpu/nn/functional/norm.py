"""Normalization functionals. Reference: python/paddle/nn/functional/norm.py."""
import jax
import jax.numpy as jnp

from ...core.dispatch import op, apply_op
from ...core.tensor import Tensor


@op
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    norm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True),
                     1.0 / p)
    return x / jnp.maximum(norm, epsilon)


@op
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@op
def group_norm_fn(x, num_groups, weight=None, bias=None, epsilon=1e-05,
                  data_format='NCHW'):
    chan_first = data_format.startswith('NC')
    if not chan_first:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    g = num_groups
    xg = jnp.reshape(x, (n, g, c // g) + spatial)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=axes, keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + epsilon)
    out = jnp.reshape(xg, (n, c) + spatial)
    if weight is not None:
        out = out * jnp.reshape(weight, (1, c) + (1,) * len(spatial))
    if bias is not None:
        out = out + jnp.reshape(bias, (1, c) + (1,) * len(spatial))
    if not chan_first:
        out = jnp.moveaxis(out, 1, -1)
    return out


@op
def instance_norm_fn(x, weight=None, bias=None, epsilon=1e-05, data_format='NCHW'):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    c = x.shape[1]
    if weight is not None:
        out = out * jnp.reshape(weight, (1, c) + (1,) * (x.ndim - 2))
    if bias is not None:
        out = out + jnp.reshape(bias, (1, c) + (1,) * (x.ndim - 2))
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format='NCHW', name=None):
    return instance_norm_fn(x, weight, bias, eps, data_format)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05, data_format='NCHW',
               use_global_stats=None, mesh_axis=None, name=None):
    """Returns output; updates running stats in-place on the provided Tensors
    when training (paddle semantics). ``mesh_axis`` (TPU extension): name of a
    mesh axis to psum stats over → SyncBatchNorm inside shard_map/pjit.
    """
    chan_axis = 1 if data_format.startswith('NC') else -1

    use_batch_stats = training and not use_global_stats

    def pure(v, w, b, rm, rv):
        axes = tuple(i for i in range(v.ndim) if i != (chan_axis % v.ndim))
        # ONE channel-broadcast shape for both the variance and the
        # normalize reshapes (review r4b: two hand-rolled copies diverge)
        shape = [1] * v.ndim
        shape[chan_axis % v.ndim] = v.shape[chan_axis % v.ndim]
        if use_batch_stats:
            mean = jnp.mean(v, axis=axes)
            # two-pass variance: the one-pass E[x^2]-mean^2 form goes
            # NEGATIVE under f32 cancellation when a channel is
            # near-constant with a large mean (true var ~1e-6 computed as
            # -1.5e-5 < -eps) -> rsqrt(negative) NaN'd a real ResNet run
            # (journey r4b, deterministic replay in the regression test)
            var = jnp.mean(jnp.square(v - jnp.reshape(mean, shape)),
                           axis=axes)
            if mesh_axis is not None:
                try:
                    # global var = pmean(E_local[x^2]) - gmean^2; the
                    # E[x^2] term must use the LOCAL mean (using the global
                    # mean here would drop the between-shard variance)
                    ex2 = jax.lax.pmean(var + jnp.square(mean), mesh_axis)
                    mean = jax.lax.pmean(mean, mesh_axis)
                    # the cross-replica merge needs the E[x^2] form; clamp
                    # the same cancellation hazard out of it
                    var = jnp.maximum(ex2 - jnp.square(mean), 0.0)
                except NameError:
                    bound = {}
                    try:
                        from jax._src.core import get_axis_env
                        bound = dict(get_axis_env().axis_sizes)
                    except Exception:   # pragma: no cover — jax internals
                        pass
                    if bound:
                        # we ARE inside a mapped context but this axis name
                        # is not bound there — a typo'd mesh_axis must be
                        # loud, not silently-local statistics
                        raise
                    # genuinely outside shard_map/pmap (eager single-device):
                    # reference SyncBatchNorm degrades to plain BatchNorm
        else:
            mean, var = rm, rv
        out = (v - jnp.reshape(mean, shape)) * jax.lax.rsqrt(
            jnp.reshape(var, shape) + epsilon)
        if w is not None:
            out = out * jnp.reshape(w, shape)
        if b is not None:
            out = out + jnp.reshape(b, shape)
        return out, mean, var

    rm = running_mean._value if isinstance(running_mean, Tensor) else running_mean
    rv = running_var._value if isinstance(running_var, Tensor) else running_var
    out, bmean, bvar = apply_op(
        lambda v, w, b: pure(v, w, b, rm, rv), x, weight, bias)
    if use_batch_stats and isinstance(running_mean, Tensor):
        m = momentum
        running_mean._replace_value(rm * m + bmean._value * (1 - m))
        running_var._replace_value(rv * m + bvar._value * (1 - m))
    return out


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format='NCHW', name=None):
    def pure(v):
        chan_first = data_format.startswith('NC')
        if not chan_first:
            v = jnp.moveaxis(v, -1, 1)
        sq = jnp.square(v)
        half = size // 2
        pad_cfg = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (v.ndim - 2)
        sq = jnp.pad(sq, pad_cfg)
        acc = sum(jnp.take(sq, jnp.arange(i, i + v.shape[1]), axis=1)
                  for i in range(size))
        out = v / jnp.power(k + alpha * acc / size, beta)
        if not chan_first:
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply_op(pure, x)
