"""Attention functionals.

``scaled_dot_product_attention`` routes to the Pallas flash-attention kernel on
TPU when shapes allow, else to the fused XLA softmax path.
Reference: python/paddle/nn/functional/ (fused attention in incubate).
"""
import math

import jax
import jax.numpy as jnp

from ...core.dispatch import op


def _sdpa_xla(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum('...qhd,...khd->...hqk', q, k) * scale
    if causal:
        qlen, klen = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((qlen, klen), jnp.bool_), k=klen - qlen)
        scores = jnp.where(cm, scores, jnp.asarray(-1e30, scores.dtype))
    if mask is not None:
        # same rank-lift rule as the flash path (key-padding masks broadcast
        # over heads/queries), so model code behaves identically either way
        from ...ops.flash_attention import lift_mask_4d
        mask = lift_mask_4d(mask)
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('...hqk,...khd->...qhd', probs, v)


@op
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """query/key/value: [batch, seq, heads, head_dim] (paddle layout)."""
    use_flash = False
    try:
        from ...ops.flash_attention import flash_attention_available
        use_flash = flash_attention_available(query, key, value, attn_mask)
    except Exception:
        use_flash = False
    if use_flash:
        from ...ops.flash_attention import flash_attention
        return flash_attention(query, key, value, causal=is_causal,
                               mask=attn_mask)
    return _sdpa_xla(query, key, value, mask=attn_mask, causal=is_causal)
