"""Attention functionals.

``scaled_dot_product_attention`` routes to the Pallas flash-attention kernel on
TPU when shapes allow, else to the fused XLA softmax path.
Reference: python/paddle/nn/functional/ (fused attention in incubate).
"""
import math

import jax
import jax.numpy as jnp

from ...core import dispatch
from ...core.dispatch import apply_op
from ...tensor.random import next_key


def _sdpa_xla(q, k, v, mask=None, dropout_p=0.0, causal=False, scale=None,
              rng=None):
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum('...qhd,...khd->...hqk', q, k) * scale
    if causal:
        qlen, klen = scores.shape[-2], scores.shape[-1]
        cm = jnp.tril(jnp.ones((qlen, klen), jnp.bool_), k=klen - qlen)
        scores = jnp.where(cm, scores, jnp.asarray(-1e30, scores.dtype))
    if mask is not None:
        # same rank-lift rule as the flash path (key-padding masks broadcast
        # over heads/queries), so model code behaves identically either way
        from ...ops.flash_attention import lift_mask_4d
        mask = lift_mask_4d(mask)
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p and rng is not None:
        if dropout_p >= 1.0:
            # everything dropped: zeros with zero grads (the 1/(1-p)
            # rescale would be inf and leak NaN through where's vjp)
            return jnp.zeros(q.shape, q.dtype)
        # inverted dropout on the attention probabilities (reference
        # fused_attention semantics)
        keep = jax.random.bernoulli(rng, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum('...hqk,...khd->...qhd', probs, v)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """query/key/value: [batch, seq, heads, head_dim] (paddle layout).

    Attention dropout (dropout_p > 0 while training) stays ON the flash
    path: the pallas kernels sample an in-kernel counter-hash mask
    (ops/flash_attention._dropout_keep) regenerated identically in the
    backward — the reference keeps dropout fused too
    (fused_attention_op.cc). dropout_p >= 1 (degenerate all-dropped)
    routes to the XLA path's zero-output semantics."""
    hook = dispatch.amp_cast_hook
    if hook is not None:
        query, key, value = hook('scaled_dot_product_attention',
                                 [query, key, value])
    drop = float(dropout_p or 0.0) if training else 0.0
    # the availability probe and both compute paths see RAW arrays; the
    # Tensor wrappers stay outside apply_op so the tape records the op
    # (review r4b: handing Tensors to flash_attention crashes on TPU)
    qv, kv, vv = (getattr(t, '_value', t) for t in (query, key, value))
    mv = getattr(attn_mask, '_value', attn_mask)
    use_flash = False
    if drop < 1.0:
        try:
            from ...ops.flash_attention import flash_attention_available
            use_flash = flash_attention_available(qv, kv, vv, mv)
        except Exception:
            use_flash = False
    # the key is drawn OUTSIDE apply_op so the tape's vjp replay sees the
    # same mask the forward sampled (the F.dropout pattern)
    rng = next_key() if drop else None
    # u32 seed for the in-kernel mask, derived once per call from the same
    # stream (traced: varies per step under jit without retracing)
    seed = jax.random.bits(rng, (1,), jnp.uint32) if (drop and use_flash) \
        else None

    def pure(q, k, v, *m):
        mask = m[0] if m else None
        if use_flash:
            from ...ops.flash_attention import flash_attention
            return flash_attention(q, k, v, causal=is_causal, mask=mask,
                                   dropout_rate=drop, dropout_seed=seed)
        return _sdpa_xla(q, k, v, mask=mask, causal=is_causal,
                         dropout_p=drop, rng=rng)

    args = ((query, key, value)
            + (() if attn_mask is None else (attn_mask,)))
    return apply_op(pure, *args)
