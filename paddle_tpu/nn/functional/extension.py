"""Extension functionals: sequence_mask, diag_embed, gather_tree,
max_unpool2d, hsigmoid, margin_cross_entropy, class_center_sample.
Reference: python/paddle/nn/functional/extension.py + loss.py.
"""
import math

import jax
import jax.numpy as jnp

from ...core.dispatch import op, apply_op
from ...core.tensor import Tensor


@op
def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    from ...core.dtype import convert_dtype
    lengths = jnp.asarray(x)
    m = maxlen if maxlen is not None else int(jnp.max(lengths))
    mask = jnp.arange(m)[None, :] < lengths[..., None]
    return mask.astype(convert_dtype(dtype))


@op
def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    x = jnp.asarray(input)
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
    out = base.at[..., r, c].set(x)
    if (dim1, dim2) != (-2, -1):
        out = jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    return out


@op
def gather_tree(ids, parents):
    """Beam-search backtrace. ids/parents: [T, B, beam]."""
    T = ids.shape[0]

    def body(carry, t):
        beams, cur_parents = carry
        idx = T - 1 - t
        tok = jnp.take_along_axis(ids[idx], cur_parents, axis=-1)
        par = jnp.take_along_axis(parents[idx], cur_parents, axis=-1)
        return (tok, par), tok

    B, W = ids.shape[1], ids.shape[2]
    init = (ids[-1], parents[-1])
    (_, _), toks = jax.lax.scan(body, (ids[-1], jnp.tile(jnp.arange(W), (B, 1))),
                                jnp.arange(T))
    return jnp.flip(toks, axis=0)


@op
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format='NCHW', output_size=None, name=None):
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    n, c, h, w = x.shape
    oh = (h - 1) * stride[0] + kernel_size[0] - 2 * padding
    ow = (w - 1) * stride[1] + kernel_size[1] - 2 * padding
    if output_size is not None:
        oh, ow = output_size[-2:]
    flat = jnp.reshape(x, (n, c, -1))
    idx = jnp.reshape(jnp.asarray(indices).astype(jnp.int32), (n, c, -1))
    base = jnp.zeros((n, c, oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(lambda b, i, v: b.at[i].set(v)))(base, idx, flat)
    return jnp.reshape(out, (n, c, oh, ow))


@op
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid with a default complete binary tree."""
    # default tree: num_classes-1 internal nodes; code of class c = binary path
    depth = max(int(math.ceil(math.log2(max(num_classes, 2)))), 1)
    lbl = jnp.asarray(label).astype(jnp.int32).reshape(-1)
    B = input.shape[0]
    # node index path: root=0; child = 2i+1 / 2i+2
    codes = []
    nodes = []
    cur = lbl + num_classes - 1          # leaf position in a heap layout
    for _ in range(depth):
        parent = (cur - 1) // 2
        is_right = (cur % 2 == 0)
        codes.append(is_right)
        nodes.append(parent)
        cur = parent
    nodes = jnp.stack(nodes, axis=1)     # [B, depth]
    codes = jnp.stack(codes, axis=1).astype(input.dtype)
    valid = nodes < (num_classes - 1)
    nodes_c = jnp.clip(nodes, 0, num_classes - 2)
    w = jnp.take(weight, nodes_c, axis=0)            # [B, depth, D]
    logits = jnp.einsum('bd,bkd->bk', input, w)
    if bias is not None:
        logits = logits + jnp.take(jnp.reshape(bias, (-1,)), nodes_c, axis=0)
    # BCE with sign from code
    loss = jnp.log1p(jnp.exp(-jnp.where(codes > 0, logits, -logits)))
    loss = jnp.where(valid, loss, 0.0)
    return jnp.sum(loss, axis=1, keepdims=True)


@op
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction='mean'):
    """ArcFace-style margin softmax. logits assumed cosine similarities."""
    lbl = jnp.asarray(label).astype(jnp.int32).reshape(-1)
    onehot = jax.nn.one_hot(lbl, logits.shape[-1], dtype=logits.dtype)
    theta = jnp.arccos(jnp.clip(logits, -1 + 1e-7, 1 - 1e-7))
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adjusted = jnp.where(onehot > 0, target, logits) * scale
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.take_along_axis(logp, lbl[:, None], axis=1)
    if reduction == 'mean':
        loss = jnp.mean(loss)
    elif reduction == 'sum':
        loss = jnp.sum(loss)
    if return_softmax:
        return loss, jax.nn.softmax(adjusted, axis=-1)
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (plus all positives)."""
    import numpy as np
    lbl = np.asarray(label._value if isinstance(label, Tensor) else label).reshape(-1)
    pos = np.unique(lbl)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    np.random.shuffle(rest)
    take = max(num_samples - len(pos), 0)
    sampled = np.sort(np.concatenate([pos, rest[:take]]))
    remap = {c: i for i, c in enumerate(sampled)}
    remapped = np.asarray([remap[c] for c in lbl], 'int64')
    return (Tensor(jnp.asarray(remapped)),
            Tensor(jnp.asarray(sampled.astype('int64'))))


def elu_(x, alpha=1.0, name=None):
    from .activation import elu
    out = elu(x, alpha)
    x._replace_value(out._value)
    return x


def tanh_(x, name=None):
    from .activation import tanh
    out = tanh(x)
    x._replace_value(out._value)
    return x
