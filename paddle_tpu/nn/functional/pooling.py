"""Pooling via lax.reduce_window. Reference: python/paddle/nn/functional/pooling.py."""
import jax
import jax.numpy as jnp

from ...core.dispatch import op


def _tuplize(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


def _pool(x, kind, kernel, stride, padding, nd, data_format, ceil_mode=False,
          exclusive=True, count_include_pad=False):
    kernel = _tuplize(kernel, nd)
    stride = _tuplize(stride if stride is not None else kernel, nd)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _tuplize(padding, nd) if not isinstance(padding, (list, tuple)) or \
            all(isinstance(q, int) for q in padding) else padding
        p = _tuplize(p, nd) if isinstance(p, int) else p
        pad = [(int(q), int(q)) if isinstance(q, int) else tuple(q) for q in p]
    chan_first = data_format.startswith('NC')
    if chan_first:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        if not isinstance(pad, str):
            pad_cfg = [(0, 0), (0, 0)] + list(pad)
    else:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        if not isinstance(pad, str):
            pad_cfg = [(0, 0)] + list(pad) + [(0, 0)]
    if isinstance(pad, str):
        pad_cfg = pad
    if kind == 'max':
        import numpy as np
        # Floating: init MUST be the plain scalar monoid identity — jax only
        # routes reduce_window to the differentiable reduce_window_max
        # primitive when it recognizes identity+computation; an array init
        # falls back to the generic primitive, which has no transpose rule
        # ("Linearization failed ..." under value_and_grad).
        # Integer: a dtype-MATCHED typed scalar (a weak python int would
        # mismatch narrow int dtypes on the generic path); integer pooling
        # is never differentiated, so losing the fast path is harmless.
        init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                else np.dtype(x.dtype).type(np.iinfo(np.dtype(x.dtype)).min))
        return jax.lax.reduce_window(x, init, jax.lax.max, window, strides,
                                     pad_cfg)
    # avg — same scalar-identity rule as max above
    zero = 0.0 if jnp.issubdtype(x.dtype, jnp.floating) else 0
    summed = jax.lax.reduce_window(x, zero, jax.lax.add, window, strides, pad_cfg)
    if exclusive and not count_include_pad and not isinstance(pad_cfg, str):
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, zero, jax.lax.add, window, strides, pad_cfg)
        return summed / counts
    denom = 1
    for k in kernel:
        denom *= k
    return summed / denom


@op
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCL', name=None):
    return _pool(x, 'max', kernel_size, stride, padding, 1, 'NC' if data_format == 'NCL' else 'NLC')


@op
def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCHW', name=None):
    return _pool(x, 'max', kernel_size, stride, padding, 2, data_format)


@op
def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format='NCDHW', name=None):
    return _pool(x, 'max', kernel_size, stride, padding, 3, data_format)


@op
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format='NCL', name=None):
    return _pool(x, 'avg', kernel_size, stride, padding, 1,
                 'NC' if data_format == 'NCL' else 'NLC', exclusive=exclusive)


@op
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCHW', name=None):
    return _pool(x, 'avg', kernel_size, stride, padding, 2, data_format,
                 exclusive=exclusive)


@op
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format='NCDHW', name=None):
    return _pool(x, 'avg', kernel_size, stride, padding, 3, data_format,
                 exclusive=exclusive)


def _adaptive(x, out_size, nd, data_format, kind):
    chan_first = data_format.startswith('NC')
    spatial = x.shape[2:2 + nd] if chan_first else x.shape[1:1 + nd]
    out_size = _tuplize(out_size, nd)
    out_size = tuple(o if o is not None else s for o, s in zip(out_size, spatial))
    # exact adaptive pooling: split into (possibly unequal) regions; use
    # mean over index ranges computed via segment trick (static shapes).
    x_ = x if chan_first else jnp.moveaxis(x, -1, 1)
    for d in range(nd):
        in_s = x_.shape[2 + d]
        out_s = out_size[d]
        starts = [(i * in_s) // out_s for i in range(out_s)]
        ends = [-(-((i + 1) * in_s) // out_s) for i in range(out_s)]
        slices = []
        for s, e in zip(starts, ends):
            seg = jnp.take(x_, jnp.arange(s, e), axis=2 + d)
            red = jnp.max(seg, axis=2 + d, keepdims=True) if kind == 'max' \
                else jnp.mean(seg, axis=2 + d, keepdims=True)
            slices.append(red)
        x_ = jnp.concatenate(slices, axis=2 + d)
    return x_ if chan_first else jnp.moveaxis(x_, 1, -1)


@op
def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, 'NCL', 'avg')


@op
def adaptive_avg_pool2d(x, output_size, data_format='NCHW', name=None):
    return _adaptive(x, output_size, 2, data_format, 'avg')


@op
def adaptive_avg_pool3d(x, output_size, data_format='NCDHW', name=None):
    return _adaptive(x, output_size, 3, data_format, 'avg')


@op
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, 'NCL', 'max')


@op
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, 'NCHW', 'max')


@op
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, 'NCDHW', 'max')
