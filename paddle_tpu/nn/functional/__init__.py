"""paddle.nn.functional parity namespace."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import (  # noqa: F401
    conv1d, conv2d, conv3d, conv1d_transpose, conv2d_transpose, conv3d_transpose)
from .pooling import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    batch_norm, instance_norm, layer_norm, local_response_norm, normalize,
    group_norm_fn, instance_norm_fn)
from .loss import *  # noqa: F401,F403
from .sparse_attention import scaled_dot_product_attention  # noqa: F401
from .extension import (  # noqa: F401
    class_center_sample, diag_embed, elu_, gather_tree, hsigmoid_loss,
    margin_cross_entropy, max_unpool2d, sequence_mask, tanh_)
