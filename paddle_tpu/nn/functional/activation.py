"""Functional activations. Reference: python/paddle/nn/functional/activation.py."""
import jax
import jax.numpy as jnp

from ...core.dispatch import op


@op
def relu(x, name=None):
    return jnp.maximum(x, 0)


relu_ = relu


@op
def relu6(x, name=None):
    return jnp.clip(x, 0, 6)


@op
def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(x, alpha)


@op
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op
def celu(x, alpha=1.0, name=None):
    return jax.nn.celu(x, alpha)


@op
def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=approximate)


@op
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


@op
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return jnp.clip(slope * x + offset, 0, 1)


@op
def hardswish(x, name=None):
    return x * jnp.clip(x / 6 + 0.5, 0, 1)


@op
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return jnp.clip(x, min, max)


@op
def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, 0)


@op
def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0))


@op
def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


@op
def leaky_relu(x, negative_slope=0.01, name=None):
    return jnp.where(x >= 0, x, negative_slope * x)


@op
def prelu(x, weight, data_format='NCHW', name=None):
    w = jnp.asarray(weight)
    if w.size > 1:
        ax = 1 if data_format == 'NCHW' else x.ndim - 1
        shape = [1] * x.ndim
        shape[ax] = w.size
        w = jnp.reshape(w, shape)
    return jnp.where(x >= 0, x, w * x)


@op
def rrelu(x, lower=0.125, upper=0.3333, training=False, name=None):
    slope = (lower + upper) / 2
    return jnp.where(x >= 0, x, slope * x)


@op
def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


@op
def maxout(x, groups, axis=1, name=None):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis:axis + 1] = [c // groups, groups]
    return jnp.max(jnp.reshape(x, shape), axis=axis + 1)


@op
def softplus(x, beta=1, threshold=20, name=None):
    return jnp.where(beta * x > threshold, x,
                     jnp.log1p(jnp.exp(beta * jnp.minimum(x, threshold / beta))) / beta)


@op
def softsign(x, name=None):
    return x / (1 + jnp.abs(x))


@op
def swish(x, name=None):
    return x * jax.nn.sigmoid(x)


@op
def silu(x, name=None):
    return x * jax.nn.sigmoid(x)


@op
def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


@op
def tanh(x, name=None):
    return jnp.tanh(x)


@op
def thresholded_relu(x, threshold=1.0, name=None):
    return jnp.where(x > threshold, x, 0)


@op
def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...core.dtype import convert_dtype
        x = x.astype(convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


softmax_ = softmax


@op
def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from ...core.dtype import convert_dtype
        x = x.astype(convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


@op
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    # NOTE: stochastic; uses a fixed fold-in of the global seed when traced.
    from ...tensor.random import next_key
    g = jax.random.gumbel(next_key(), x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis], axis=axis,
                                dtype=y.dtype)
        y = jax.lax.stop_gradient(y_hard - y) + y
    return y


@op
def glu(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)
