"""paddle.nn parity namespace."""
from .layer_base import Layer, Parameter, ParamAttr, functional_call, param_arrays, buffer_arrays  # noqa: F401
from .layer_common import (  # noqa: F401
    AlphaDropout, Bilinear, CosineSimilarity, Dropout, Dropout2D, Dropout3D,
    Embedding, Flatten, Identity, Linear, Pad1D, Pad2D, Pad3D,
    PairwiseDistance, PixelShuffle, Unfold, Upsample, UpsamplingBilinear2D,
    UpsamplingNearest2D, ZeroPad2D)
from .layer_conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose)
from .layer_norm_layers import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, SpectralNorm, SyncBatchNorm)
from .layer_pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
    AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D)
from .layer_activation import (  # noqa: F401
    CELU, ELU, GELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh, LeakyReLU,
    LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, RReLU, SELU,
    Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
    Tanhshrink, ThresholdedReLU)
from .layer_loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss, CTCLoss,
    HingeEmbeddingLoss, KLDivLoss, L1Loss, MarginRankingLoss, MSELoss, NLLLoss,
    SmoothL1Loss, TripletMarginLoss)
from .layer_container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layer_rnn import (  # noqa: F401
    BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, SimpleRNN, SimpleRNNCell)
from .layer_transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer)
from .clip import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue, clip_grad_norm_)
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .layer_rnn import _CellBase as RNNCellBase  # noqa: F401
from . import layer_loss as loss  # noqa: F401
from .utils import spectral_norm, weight_norm, remove_weight_norm  # noqa: F401
from .layer_base import Layer as _LayerForExtras
from . import quant  # noqa: F401


class HSigmoidLoss(_LayerForExtras):
    """Reference: python/paddle/nn/layer/loss.py:HSigmoidLoss."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter((num_classes - 1, feature_size),
                                            weight_attr)
        self.bias = self.create_parameter((num_classes - 1, 1), bias_attr,
                                          is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return functional.hsigmoid_loss(input, label, self.num_classes,
                                        self.weight, self.bias)


class MaxUnPool2D(_LayerForExtras):
    def __init__(self, kernel_size, stride=None, padding=0, data_format='NCHW',
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, osz = self._args
        return functional.max_unpool2d(x, indices, k, s, p, df, osz)
from ..utils.deprecated import deprecated  # noqa: F401,E402  (reference nn/__init__ re-export)
