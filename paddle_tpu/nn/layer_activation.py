"""Activation layers. Reference: python/paddle/nn/layer/activation.py."""
from .layer_base import Layer
from . import functional as F
from . import initializer as I


def _make(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            kwargs.pop('name', None)
            merged = dict(defaults)
            keys = list(defaults.keys())
            for i, a in enumerate(args):
                merged[keys[i]] = a
            merged.update(kwargs)
            self._kw = merged

        def forward(self, x):
            return fn(x, **self._kw)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


CELU = _make('CELU', F.celu, alpha=1.0)
ELU = _make('ELU', F.elu, alpha=1.0)
GELU = _make('GELU', F.gelu, approximate=False)
Hardshrink = _make('Hardshrink', F.hardshrink, threshold=0.5)
Hardswish = _make('Hardswish', F.hardswish)
Hardtanh = _make('Hardtanh', F.hardtanh, min=-1.0, max=1.0)
Hardsigmoid = _make('Hardsigmoid', F.hardsigmoid)
LeakyReLU = _make('LeakyReLU', F.leaky_relu, negative_slope=0.01)
LogSigmoid = _make('LogSigmoid', F.log_sigmoid)
LogSoftmax = _make('LogSoftmax', F.log_softmax, axis=-1)
Maxout = _make('Maxout', F.maxout, groups=2, axis=1)
Mish = _make('Mish', F.mish)
ReLU = _make('ReLU', F.relu)
ReLU6 = _make('ReLU6', F.relu6)
SELU = _make('SELU', F.selu)
Sigmoid = _make('Sigmoid', F.sigmoid)
Silu = _make('Silu', F.silu)
Softmax = _make('Softmax', F.softmax, axis=-1)
Softplus = _make('Softplus', F.softplus, beta=1, threshold=20)
Softshrink = _make('Softshrink', F.softshrink, threshold=0.5)
Softsign = _make('Softsign', F.softsign)
Swish = _make('Swish', F.swish)
Tanh = _make('Tanh', F.tanh)
Tanhshrink = _make('Tanhshrink', F.tanhshrink)
ThresholdedReLU = _make('ThresholdedReLU', F.thresholded_relu, threshold=1.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format='NCHW', name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), weight_attr, default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1. / 8., upper=1. / 3., name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
