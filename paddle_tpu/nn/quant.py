"""Quantization-aware training layers.

Reference: python/paddle/nn/quant/ (FakeQuantAbsMax, QuantizedLinear/Conv2D
in fluid contrib slim). TPU-native: fake-quant is a straight-through
estimator expressed in jnp (int8 simulated in fp); real int8 serving comes
from XLA's native int8 matmul when weights are pre-quantized.
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import op, apply_op
from .layer_base import Layer
from .layer_common import Embedding, Linear
from .layer_conv import Conv2D


def _ste(x, q):
    """straight-through: forward q, backward identity."""
    return x + jax.lax.stop_gradient(q - x)


@op
def fake_quantize_abs_max(x, bits=8, name=None):
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.max(jnp.abs(x)) / qmax
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x / scale) * scale
    return _ste(x, q)


@op
def fake_channel_wise_quantize_abs_max(x, bits=8, axis=0, name=None):
    qmax = 2.0 ** (bits - 1) - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True) / qmax
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x / scale) * scale
    return _ste(x, q)


@op
def fake_quantize_moving_average_abs_max(x, state_scale, bits=8, name=None):
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.asarray(state_scale) / qmax, 1e-8)
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax) * scale
    return _ste(x, q)


class FakeQuantAbsMax(Layer):
    def __init__(self, quant_bits=8, dtype='float32', name=None):
        super().__init__()
        self.bits = quant_bits

    def forward(self, x):
        return fake_quantize_abs_max(x, self.bits)


class _QuantWrapperBase(Layer):
    """Shared QAT wrapper: weight fake-quant (per-tensor or per-channel
    abs_max) + activation fake-quant (per-batch abs_max or moving-average
    observer kept in a buffer, reference
    fluid/contrib/slim/quantization/imperative/qat.py semantics)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 weight_quantize_type='channel_wise_abs_max',
                 activation_quantize_type='abs_max', moving_rate=0.9,
                 observe_only=False):
        super().__init__()
        from ..core.tensor import Tensor
        self.inner = layer
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self._w_type = weight_quantize_type
        self._a_type = activation_quantize_type
        self._rate = moving_rate
        self._observe_only = observe_only   # PTQ calibration: collect scales,
        #                                     pass activations through unquantized
        if activation_quantize_type == 'moving_average_abs_max' or observe_only:
            self.register_buffer('_act_scale',
                                 Tensor(jnp.zeros((), jnp.float32)))

    def _quant_act(self, x):
        if self._a_type == 'moving_average_abs_max' or self._observe_only:
            if self.training or self._observe_only:
                cur = x.abs().max()
                old = self._act_scale._value
                # first observation seeds the scale instead of averaging
                # against the zero init
                new = jnp.where(old > 0,
                                self._rate * old + (1 - self._rate) * cur._value,
                                cur._value)
                self._act_scale._replace_value(new.astype(jnp.float32))
            if self._observe_only:
                return x
            return fake_quantize_moving_average_abs_max(
                x, self._act_scale._value, self.activation_bits)
        return fake_quantize_abs_max(x, self.activation_bits)

    def _quant_weight(self, w, channel_axis):
        if self._observe_only:
            return w
        if self._w_type == 'abs_max':
            return fake_quantize_abs_max(w, self.weight_bits)
        return fake_channel_wise_quantize_abs_max(w, self.weight_bits,
                                                  axis=channel_axis)


class QuantizedLinear(_QuantWrapperBase):
    """Linear with fake-quantized weights+activations (QAT)."""

    def forward(self, x):
        from . import functional as F
        xq = self._quant_act(x)
        wq = self._quant_weight(self.inner.weight, channel_axis=1)
        return F.linear(xq, wq, self.inner.bias)


class QuantizedConv2D(_QuantWrapperBase):
    def forward(self, x):
        from . import functional as F
        xq = self._quant_act(x)
        wq = self._quant_weight(self.inner.weight, channel_axis=0)
        return F.conv2d(xq, wq, self.inner.bias,
                        self.inner._stride, self.inner._padding,
                        self.inner._dilation, self.inner._groups,
                        self.inner._data_format)


class WeightOnlyLinear(Layer):
    """Serving-time Linear whose weight is stored as int8 with one f32
    scale per output channel (ops/weight_only.py). Dequantization folds
    into the matmul epilogue — ``(x @ q) * s`` — so HBM streams half the
    bytes of bf16; the bias (and gradients to ``x``) stay full precision.
    The int8/scale pair are BUFFERS: they serialize through state_dict /
    jit.save and are constants to the autograd tape."""

    def __init__(self, layer, act_scale=None, act_bits=8):
        super().__init__()
        from ..core.tensor import Tensor
        from ..ops.weight_only import quantize_weight
        q = quantize_weight(layer.weight._value, reduce_axis=0)
        self.register_buffer('weight_int8', Tensor(q['int8']))
        self.register_buffer('weight_scale', Tensor(q['scale']))
        self.bias = layer.bias
        self.in_features = layer.in_features
        self.out_features = layer.out_features
        # calibrated activation quantization (PTQ convert_calibrated):
        # when a scale was observed, inputs fake-quant against it so the
        # served numerics match the calibrated int8 activation grid
        self.act_bits = act_bits
        if act_scale is not None:
            self.register_buffer('act_scale',
                                 Tensor(jnp.float32(act_scale)))
        else:
            self.act_scale = None

    def forward(self, x):
        if self.act_scale is not None:
            x = fake_quantize_moving_average_abs_max(
                x, self.act_scale._value, self.act_bits)

        def pure(xv, qv, sv, bv=None):
            y = (xv @ qv.astype(xv.dtype)) * sv.astype(xv.dtype)
            return y if bv is None else y + bv.astype(xv.dtype)
        args = [x, self.weight_int8, self.weight_scale]
        if self.bias is not None:
            args.append(self.bias)
        return apply_op(pure, *args)

    def extra_repr(self):
        return (f'in_features={self.in_features}, '
                f'out_features={self.out_features}, weight=int8')


class WeightOnlyConv2D(Layer):
    """Serving-time Conv2D with an int8 weight bank and per-OUTPUT-CHANNEL
    f32 scales (amax over in/kh/kw): the scale multiplies the conv output
    channel — the same epilogue position as the bias — so XLA streams int8
    weight bytes and fuses the dequant. Eval/serving only."""

    def __init__(self, layer, act_scale=None, act_bits=8):
        super().__init__()
        from ..core.tensor import Tensor
        from ..ops.weight_only import quantize_weight
        q = quantize_weight(layer.weight._value, reduce_axis=(1, 2, 3))
        self.register_buffer('weight_int8', Tensor(q['int8']))
        self.register_buffer('weight_scale', Tensor(q['scale']))
        self.bias = layer.bias
        for a in ('_stride', '_padding', '_dilation', '_groups',
                  '_data_format'):
            setattr(self, a, getattr(layer, a))
        self.act_bits = act_bits
        if act_scale is not None:
            self.register_buffer('act_scale',
                                 Tensor(jnp.float32(act_scale)))
        else:
            self.act_scale = None

    def forward(self, x):
        from .functional.conv import _conv
        if self.act_scale is not None:
            x = fake_quantize_moving_average_abs_max(
                x, self.act_scale._value, self.act_bits)
        st, pd, dl, gp, df = (self._stride, self._padding, self._dilation,
                              self._groups, self._data_format)
        channels_last = df.endswith('C')    # 'NHWC'; 'NCHW' ends with 'W'

        def pure(xv, qv, sv, bv=None):
            y = _conv(xv, qv.astype(xv.dtype), None, st, pd, dl, gp, df, 2)
            shape = ((1,) * (y.ndim - 1) + (-1,) if channels_last
                     else (1, -1) + (1,) * (y.ndim - 2))
            y = y * jnp.reshape(sv, shape).astype(y.dtype)
            if bv is not None:
                y = y + jnp.reshape(bv, shape).astype(y.dtype)
            return y
        args = [x, self.weight_int8, self.weight_scale]
        if self.bias is not None:
            args.append(self.bias)
        return apply_op(pure, *args)


class WeightOnlyEmbedding(Layer):
    """Serving-time Embedding with an int8 table and one f32 scale per ROW
    (per-token-id): lookups stream int8 rows out of HBM and dequantize in
    registers. padding_idx rows zero exactly, matching F.embedding."""

    def __init__(self, layer):
        super().__init__()
        from ..core.tensor import Tensor
        from ..ops.weight_only import quantize_weight
        q = quantize_weight(layer.weight._value, reduce_axis=1)
        self.register_buffer('weight_int8', Tensor(q['int8']))
        self.register_buffer('weight_scale', Tensor(q['scale']))
        self._padding_idx = layer._padding_idx

    def forward(self, x):
        pad = self._padding_idx

        def pure(idx, qv, sv):
            idx = jnp.asarray(idx).astype(jnp.int32)
            rows = (jnp.take(qv, idx, axis=0).astype(sv.dtype)
                    * jnp.take(sv, idx, axis=0)[..., None])
            if pad is not None:
                rows = jnp.where((idx == pad)[..., None], 0.0, rows)
            return rows
        return apply_op(pure, x, self.weight_int8, self.weight_scale)

    def extra_repr(self):
        v, h = self.weight_int8.shape
        return f'num_embeddings={v}, embedding_dim={h}, weight=int8'


_WO_WRAPPERS = ((Linear, WeightOnlyLinear), (Conv2D, WeightOnlyConv2D),
                (Embedding, WeightOnlyEmbedding))
_WO_TYPES = (WeightOnlyLinear, WeightOnlyConv2D, WeightOnlyEmbedding)


def weight_only_quantize(model, layer_types=(Linear, Conv2D)):
    """Swap Linear/Conv2D sublayers for their weight-only int8 forms in
    place (serving-time int8 — the reference's inference int8 precision
    mode, paddle_analysis_config.h Precision::kInt8, redesigned for the
    HBM-bound TPU serving path). ``layer_types`` narrows the swap to
    subclasses of Linear / Conv2D. Returns the model; intended for
    eval/serving — training through the quantized weights is not defined."""
    bad = [t for t in layer_types
           if not issubclass(t, tuple(b for b, _ in _WO_WRAPPERS))]
    if bad:
        raise TypeError(
            f'weight_only_quantize: {[t.__name__ for t in bad]} are not '
            'Linear/Conv2D/Embedding subclasses — only those weight '
            'layouts have a weight-only int8 form here')
    types = tuple(layer_types)
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, _WO_TYPES + (_QuantWrapperBase,)):
            # QAT/PTQ wrappers already model int8 numerics (and their inner
            # layer's weight must stay live for the fake-quant forward)
            continue
        if isinstance(sub, types):
            for base, wrapper in _WO_WRAPPERS:
                if isinstance(sub, base):
                    model._sub_layers[name] = wrapper(sub)
                    break
        else:
            weight_only_quantize(sub, layer_types=layer_types)
    return model


def convert_calibrated(model):
    """Swap calibrated QAT/PTQ wrappers (``_QuantWrapperBase``) for real
    weight-only int8 layers in place: the inner layer's weight is snapshot
    to int8 + per-output-channel scales, and an observed activation scale
    (``_act_scale`` > 0) rides along so inputs fake-quant against the
    calibrated grid. This is the conversion step the reference's
    ``quant_post_dynamic`` / ``PostTrainingQuantization.quantize()``
    perform — after it, the model genuinely serves int8 weights."""
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, _QuantWrapperBase):
            act_scale = None
            if hasattr(sub, '_act_scale'):
                s = float(sub._act_scale._value)
                if s > 0:
                    act_scale = s
            inner = sub.inner
            if isinstance(inner, Linear):
                model._sub_layers[name] = WeightOnlyLinear(
                    inner, act_scale=act_scale, act_bits=sub.activation_bits)
            elif isinstance(inner, Conv2D):
                model._sub_layers[name] = WeightOnlyConv2D(
                    inner, act_scale=act_scale, act_bits=sub.activation_bits)
            else:
                # no weight-only form for this layout: drop the wrapper,
                # keep the full-precision inner layer
                model._sub_layers[name] = inner
        else:
            convert_calibrated(sub)
    return model


def quantize_model(model, weight_bits=8, activation_bits=8,
                   layer_types=(Linear, Conv2D), **quant_kw):
    """Swap quantizable sublayers for QAT-wrapped versions in place.
    Already-wrapped layers are left alone, so a second pass (or PTQ after
    QAT) never double-wraps."""
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, _QuantWrapperBase):
            continue
        if isinstance(sub, layer_types) and isinstance(sub, Linear):
            model._sub_layers[name] = QuantizedLinear(
                sub, weight_bits, activation_bits, **quant_kw)
        elif isinstance(sub, layer_types) and isinstance(sub, Conv2D):
            model._sub_layers[name] = QuantizedConv2D(
                sub, weight_bits, activation_bits, **quant_kw)
        else:
            quantize_model(sub, weight_bits, activation_bits,
                           layer_types=layer_types, **quant_kw)
    return model
