"""Quantization-aware training layers.

Reference: python/paddle/nn/quant/ (FakeQuantAbsMax, QuantizedLinear/Conv2D
in fluid contrib slim). TPU-native: fake-quant is a straight-through
estimator expressed in jnp (int8 simulated in fp); real int8 serving comes
from XLA's native int8 matmul when weights are pre-quantized.
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import op, apply_op
from .layer_base import Layer
from .layer_common import Linear
from .layer_conv import Conv2D


def _ste(x, q):
    """straight-through: forward q, backward identity."""
    return x + jax.lax.stop_gradient(q - x)


@op
def fake_quantize_abs_max(x, bits=8, name=None):
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.max(jnp.abs(x)) / qmax
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x / scale) * scale
    return _ste(x, q)


@op
def fake_channel_wise_quantize_abs_max(x, bits=8, axis=0, name=None):
    qmax = 2.0 ** (bits - 1) - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True) / qmax
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x / scale) * scale
    return _ste(x, q)


@op
def fake_quantize_moving_average_abs_max(x, state_scale, bits=8, name=None):
    qmax = 2.0 ** (bits - 1) - 1
    scale = jnp.maximum(jnp.asarray(state_scale) / qmax, 1e-8)
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax) * scale
    return _ste(x, q)


class FakeQuantAbsMax(Layer):
    def __init__(self, quant_bits=8, dtype='float32', name=None):
        super().__init__()
        self.bits = quant_bits

    def forward(self, x):
        return fake_quantize_abs_max(x, self.bits)


class QuantizedLinear(Layer):
    """Linear with fake-quantized weights+activations (QAT)."""

    def __init__(self, layer: Linear, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = layer
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def forward(self, x):
        from . import functional as F
        xq = fake_quantize_abs_max(x, self.activation_bits)
        wq = fake_channel_wise_quantize_abs_max(self.inner.weight,
                                                self.weight_bits, axis=1)
        return F.linear(xq, wq, self.inner.bias)


class QuantizedConv2D(Layer):
    def __init__(self, layer: Conv2D, weight_bits=8, activation_bits=8):
        super().__init__()
        self.inner = layer
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits

    def forward(self, x):
        from . import functional as F
        xq = fake_quantize_abs_max(x, self.activation_bits)
        wq = fake_channel_wise_quantize_abs_max(self.inner.weight,
                                                self.weight_bits, axis=0)
        return F.conv2d(xq, wq, self.inner.bias,
                        self.inner._stride, self.inner._padding,
                        self.inner._dilation, self.inner._groups,
                        self.inner._data_format)


def quantize_model(model, weight_bits=8, activation_bits=8):
    """Swap Linear/Conv2D sublayers for QAT-wrapped versions in place."""
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, Linear):
            model._sub_layers[name] = QuantizedLinear(sub, weight_bits,
                                                      activation_bits)
        elif isinstance(sub, Conv2D):
            model._sub_layers[name] = QuantizedConv2D(sub, weight_bits,
                                                      activation_bits)
        else:
            quantize_model(sub, weight_bits, activation_bits)
    return model
