"""nn.utils: weight_norm / spectral_norm reparameterizations.

Reference: python/paddle/nn/utils/weight_norm_hook.py, spectral_norm_hook.py.
"""
import jax.numpy as jnp

from ..core.dispatch import apply_op
from .layer_base import Parameter


def _norm_except(v, dim):
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(v), axis=axes, keepdims=True))


def weight_norm(layer, name='weight', dim=0):
    w = layer._parameters.pop(name)
    dim = dim if dim is not None else 0
    g0 = _norm_except(w._value, dim)
    layer.add_parameter(name + '_g', Parameter(g0))
    layer.add_parameter(name + '_v', Parameter(w._value))

    def hook(lyr, inputs):
        g = lyr._parameters[name + '_g']
        v = lyr._parameters[name + '_v']
        w_t = apply_op(lambda gv, vv: vv * (gv / _norm_except(vv, dim)), g, v)
        object.__setattr__(lyr, name, w_t)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name='weight'):
    g = layer._parameters.pop(name + '_g')
    v = layer._parameters.pop(name + '_v')
    w = v._value * (g._value / _norm_except(v._value, 0))
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, Parameter(w))
    if getattr(layer, '_weight_norm_handle', None) is not None:
        layer._weight_norm_handle.remove()
    return layer


def spectral_norm(layer, name='weight', n_power_iterations=1, eps=1e-12, dim=None):
    from .layer_norm_layers import SpectralNorm
    w = layer._parameters.pop(name)
    if dim is None:
        dim = 0
    sn = SpectralNorm(w.shape, dim=dim, power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(name + '_sn', sn)
    layer.add_parameter(name + '_orig', w)

    def hook(lyr, inputs):
        w_t = lyr._sub_layers[name + '_sn'](lyr._parameters[name + '_orig'])
        object.__setattr__(lyr, name, w_t)
        return None

    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer
