"""RNN layers via lax.scan (compile-friendly recurrence).

Reference: python/paddle/nn/layer/rnn.py. The reference runs per-timestep
kernels (or cuDNN); here the whole sequence is one lax.scan so XLA fuses the
gate GEMMs per step and pipelines HBM reads.
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from .layer_base import Layer
from . import initializer as I


def _cell_step(mode, w_ih, w_hh, b_ih, b_hh):
    def simple(x_t, h):
        (h_prev,) = h
        h_new = jnp.tanh(x_t @ w_ih.T + h_prev @ w_hh.T + b_ih + b_hh)
        return (h_new,), h_new

    def lstm(x_t, state):
        h_prev, c_prev = state
        gates = x_t @ w_ih.T + h_prev @ w_hh.T + b_ih + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c = f * c_prev + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return (h, c), h

    def gru(x_t, state):
        (h_prev,) = state
        gi = x_t @ w_ih.T + b_ih
        gh = h_prev @ w_hh.T + b_hh
        i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
        h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(i_r + h_r)
        z = jax.nn.sigmoid(i_z + h_z)
        n = jnp.tanh(i_n + r * h_n)
        h = (1 - z) * n + z * h_prev
        return (h,), h

    return {'RNN_TANH': simple, 'LSTM': lstm, 'GRU': gru}[mode]


class _RNNBase(Layer):
    MODE = 'RNN_TANH'
    GATES = 1
    STATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1, direction='forward',
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ('bidirect', 'bidirectional')
        ndir = 2 if self.bidirect else 1
        self.num_directions = ndir
        g = self.GATES
        k = 1.0 / (hidden_size ** 0.5)
        for layer in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if layer == 0 else hidden_size * ndir
                sfx = f'_reverse' if d == 1 else ''
                self.add_parameter(
                    f'weight_ih_l{layer}{sfx}',
                    self.create_parameter((g * hidden_size, in_sz),
                                          weight_ih_attr,
                                          default_initializer=I.Uniform(-k, k)))
                self.add_parameter(
                    f'weight_hh_l{layer}{sfx}',
                    self.create_parameter((g * hidden_size, hidden_size),
                                          weight_hh_attr,
                                          default_initializer=I.Uniform(-k, k)))
                self.add_parameter(
                    f'bias_ih_l{layer}{sfx}',
                    self.create_parameter((g * hidden_size,), bias_ih_attr,
                                          default_initializer=I.Uniform(-k, k)))
                self.add_parameter(
                    f'bias_hh_l{layer}{sfx}',
                    self.create_parameter((g * hidden_size,), bias_hh_attr,
                                          default_initializer=I.Uniform(-k, k)))

    def _weights(self, layer, reverse):
        sfx = '_reverse' if reverse else ''
        return tuple(self._parameters[f'{n}_l{layer}{sfx}']
                     for n in ('weight_ih', 'weight_hh', 'bias_ih', 'bias_hh'))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.MODE
        nl, ndir, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        n_states = self.STATES

        all_params = []
        for layer in range(nl):
            for d in range(ndir):
                all_params.extend(self._weights(layer, d == 1))

        init = None
        if initial_states is not None:
            raw = initial_states if isinstance(initial_states, (list, tuple)) \
                else (initial_states,)
            init = tuple(s._value if isinstance(s, Tensor) else jnp.asarray(s)
                         for s in raw)

        def pure(x, *flat_w):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)        # -> [T, B, C]
            B = x.shape[1]
            widx = 0
            outs = x
            finals_h = []
            finals_c = []
            for layer in range(nl):
                layer_outs = []
                for d in range(ndir):
                    w_ih, w_hh, b_ih, b_hh = flat_w[widx:widx + 4]
                    widx += 4
                    step = _cell_step(mode, w_ih, w_hh, b_ih, b_hh)
                    if init is not None:
                        h0 = tuple(jnp.asarray(s)[layer * ndir + d] for s in init)
                    else:
                        h0 = tuple(jnp.zeros((B, hs), x.dtype) for _ in range(n_states))
                    seq = jnp.flip(outs, 0) if d == 1 else outs
                    final, ys = jax.lax.scan(lambda c, xt: step(xt, c), h0, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    layer_outs.append(ys)
                    finals_h.append(final[0])
                    if n_states == 2:
                        finals_c.append(final[1])
                outs = jnp.concatenate(layer_outs, axis=-1) if ndir == 2 else layer_outs[0]
            if not time_major:
                outs = jnp.swapaxes(outs, 0, 1)
            h_n = jnp.stack(finals_h, 0)
            if n_states == 2:
                return outs, h_n, jnp.stack(finals_c, 0)
            return outs, h_n

        res = apply_op(pure, inputs, *all_params)
        if n_states == 2:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    MODE = 'RNN_TANH'
    GATES = 1
    STATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1, direction='forward',
                 time_major=False, dropout=0.0, activation='tanh', **kw):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kw)


class LSTM(_RNNBase):
    MODE = 'LSTM'
    GATES = 4
    STATES = 2


class GRU(_RNNBase):
    MODE = 'GRU'
    GATES = 3
    STATES = 1


class _CellBase(Layer):
    MODE = 'RNN_TANH'
    GATES = 1
    STATES = 1

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        g = self.GATES
        k = 1.0 / (hidden_size ** 0.5)
        self.weight_ih = self.create_parameter((g * hidden_size, input_size),
                                               weight_ih_attr,
                                               default_initializer=I.Uniform(-k, k))
        self.weight_hh = self.create_parameter((g * hidden_size, hidden_size),
                                               weight_hh_attr,
                                               default_initializer=I.Uniform(-k, k))
        self.bias_ih = self.create_parameter((g * hidden_size,), bias_ih_attr,
                                             default_initializer=I.Uniform(-k, k))
        self.bias_hh = self.create_parameter((g * hidden_size,), bias_hh_attr,
                                             default_initializer=I.Uniform(-k, k))

    def forward(self, inputs, states=None):
        n_states = self.STATES
        mode = self.MODE
        hs = self.hidden_size

        def pure(x, w_ih, w_hh, b_ih, b_hh, *state):
            if not state:
                state = tuple(jnp.zeros((x.shape[0], hs), x.dtype)
                              for _ in range(n_states))
            step = _cell_step(mode, w_ih, w_hh, b_ih, b_hh)
            new_state, y = step(x, state)
            return (y,) + tuple(new_state)

        state_args = []
        if states is not None:
            state_args = list(states) if isinstance(states, (list, tuple)) else [states]
        res = apply_op(pure, inputs, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh, *state_args)
        y = res[0]
        new_states = res[1:]
        if n_states == 1:
            return y, new_states[0]
        return y, tuple(new_states)


class SimpleRNNCell(_CellBase):
    MODE = 'RNN_TANH'
    GATES = 1
    STATES = 1


class LSTMCell(_CellBase):
    MODE = 'LSTM'
    GATES = 4
    STATES = 2


class GRUCell(_CellBase):
    MODE = 'GRU'
    GATES = 3
    STATES = 1


class RNN(Layer):
    """Wraps a cell into a sequence scanner. Reference: nn/layer/rnn.py:RNN."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        axis = 0 if self.time_major else 1
        T = inputs.shape[axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        outs = []
        state = initial_states
        from ..tensor.manipulation import stack
        for t in steps:
            x_t = inputs[t] if self.time_major else inputs[:, t]
            y, state = self.cell(x_t, state)
            outs.append(y)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=axis), state


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        from ..tensor.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
