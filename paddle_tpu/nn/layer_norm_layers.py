"""Norm layers. Reference: python/paddle/nn/layer/norm.py."""
import jax.numpy as jnp

from ..core.tensor import Tensor
from .layer_base import Layer
from . import functional as F
from . import initializer as I


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_features,), bias_attr, is_bias=True)
        self.register_buffer('_mean', Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer('_variance', Tensor(jnp.ones((num_features,), jnp.float32)))
        self._mesh_axis = None   # set by SyncBatchNorm / parallel wrappers

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats,
            mesh_axis=self._mesh_axis)


class BatchNorm(_BatchNormBase):
    """fluid-style BatchNorm (accepts act)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype='float32',
                 data_layout='NCHW', in_place=False, use_global_stats=False,
                 **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == 'relu':
            return F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCDHW',
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BatchNorm: stats are pmean'd over the data-parallel mesh
    axis when run inside shard_map/pjit (TPU-native replacement for the
    reference's NCCL sync_batch_norm, paddle/fluid/operators/sync_batch_norm_op.cu)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW', name=None,
                 mesh_axis='dp'):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format)
        self._mesh_axis = mesh_axis

    @classmethod
    def convert_sync_batchnorm(cls, layer, mesh_axis='dp'):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format, mesh_axis=mesh_axis)
            new.weight = layer.weight
            new.bias = layer.bias
            new.register_buffer('_mean', layer._mean)
            new.register_buffer('_variance', layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub, mesh_axis)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape, bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW', name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_channels,), weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter((num_channels,), bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm_fn(x, self._num_groups, self.weight, self.bias,
                               self._epsilon, self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCL', name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), weight_attr, default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter((num_features,), bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm_fn(x, self.weight, self.bias, self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCHW', name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCDHW', name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format='NCHW', name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight tensor.
    Reference: python/paddle/nn/layer/norm.py:SpectralNorm."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype='float32'):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        from ..tensor.random import next_key
        import jax
        self.register_buffer('weight_u', Tensor(
            jax.random.normal(next_key(), (h,), jnp.float32)))
        self.register_buffer('weight_v', Tensor(
            jax.random.normal(next_key(), (w,), jnp.float32)))

    def forward(self, weight):
        from ..core.dispatch import apply_op
        import jax
        dim, eps, iters = self._dim, self._eps, self._power_iters
        u0 = self.weight_u._value
        v0 = self.weight_v._value

        def pure(wt):
            wmat = jnp.moveaxis(wt, dim, 0)
            shape = wmat.shape
            wmat = jnp.reshape(wmat, (shape[0], -1))
            u, v = u0, v0
            for _ in range(iters):
                v = wmat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wmat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            # u/v are power-iteration STATE, not part of the graph: sigma's
            # gradient flows only through wmat (reference semantics)
            u = jax.lax.stop_gradient(u)
            v = jax.lax.stop_gradient(v)
            sigma = u @ wmat @ v
            out = wt / sigma
            return out, u, v
        out, u_new, v_new = apply_op(pure, weight)
        # persist the refined vectors — each forward must CONTINUE the power
        # iteration, not restart it from the initial random draw (journey
        # r4b: sigma stayed ~70% off after any number of calls)
        self.weight_u._replace_value(u_new._value)
        self.weight_v._replace_value(v_new._value)
        return out
