"""Sequence decoding: beam search. Reference: python/paddle/nn/decode.py
(BeamSearchDecoder + dynamic_decode over RNN cells).

TPU-native: the decode loop is a lax.while-free bounded Python loop over the
jitted cell step (static max_step_num), with log-prob beam bookkeeping in
jnp — no dynamic shapes.
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor


class BeamSearchDecoder:
    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        def pure(v):
            v = jnp.repeat(v[:, None], beam_size, axis=1)
            return jnp.reshape(v, (-1,) + v.shape[2:])
        return apply_op(pure, x)


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Greedy-beam decode driving an RNN cell. Returns (ids, final_scores)."""
    cell = decoder.cell
    beam = decoder.beam_size
    end = decoder.end_token

    # initial state: batch-expanded to beams
    state = inits
    batch = None
    ids = None
    scores = None

    for step in range(max_step_num):
        if ids is None:
            # first step: start tokens
            if state is not None:
                s0 = state[0] if isinstance(state, (tuple, list)) else state
                batch = s0.shape[0]
            else:
                batch = 1
            tok = Tensor(jnp.full((batch,), decoder.start_token, jnp.int32))
            ids = jnp.zeros((batch, 0), jnp.int32)
            scores = jnp.zeros((batch,), jnp.float32)
        emb = decoder.embedding_fn(tok) if decoder.embedding_fn else tok
        out, state = cell(emb, state)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        logits_v = logits._value if isinstance(logits, Tensor) else jnp.asarray(logits)
        logp = jax.nn.log_softmax(logits_v.astype(jnp.float32), axis=-1)
        nxt = jnp.argmax(logp, axis=-1)
        scores = scores + jnp.take_along_axis(logp, nxt[:, None], axis=1)[:, 0]
        ids = jnp.concatenate([ids, nxt[:, None].astype(jnp.int32)], axis=1)
        tok = Tensor(nxt.astype(jnp.int32))
        if bool(jnp.all(nxt == end)):
            break
    return Tensor(ids), Tensor(scores)
