"""Dynamic-to-static control-flow conversion (dy2static).

Reference: python/paddle/fluid/dygraph/dygraph_to_static/
(ifelse_transformer.py, loop_transformer.py, convert_operators.py): the
reference rewrites Python ``if``/``while`` on tensors into cond/while ops in
its ProgramDesc. TPU-native: the same AST rewrite, but targeting
``lax.cond`` / ``lax.while_loop`` — XLA's native structured control flow —
with a runtime dispatch that preserves plain-Python semantics whenever the
condition is NOT a traced tensor, so eager behaviour is unchanged.

Scope: tensor-conditioned ``if``/``else``, ``while``, ``for .. in
range(...)`` (→ lax.cond / lax.while_loop), ``and``/``or``/``not`` in
conditions (→ jnp.logical_* when traced, exact short-circuit otherwise),
and ``break``/``continue`` in loops (lowered to flag variables + guards by
a pre-pass — the reference's break_continue_transformer.py — so a
tensor-conditioned break becomes loop-carried lax state; a ``for range``
containing break lowers to its while-form first; break/continue inside
``except`` handlers and loop-``else`` blocks are seen too), early
``return`` in tensor branches (single-exit lowering: the statements after
the if become the else-continuation — _ReturnLowering, the reference's
return_transformer.py), and attribute/subscript stores via slot
localization (``self.n = ...`` in a tensor branch/loop round-trips as a
loop carrier), and ``return`` inside a LOOP body — lowered to a flag +
break + post-loop re-emission of the return expression
(_LoopReturnLowering), so tensor-conditioned loop returns become lax
state with no value carrier to synthesize. Still-unsupported constructs
(a var bound in only one branch) raise Dy2StaticError with an actionable
message instead of jax's TracerBoolConversionError.
"""
import ast
import functools
import inspect
import textwrap
import types
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ['convert_control_flow', 'Dy2StaticError']

_RT_NAME = '_pt_dy2st'          # name the runtime is injected under
_GEN_PREFIX = '_pt_'            # prefix of every generated symbol
_ATTR_PREFIX = f'{_GEN_PREFIX}attr'   # localized attribute/subscript slots


class Dy2StaticError(Exception):
    pass


class _Undef:
    """Sentinel for 'name unbound before the control-flow statement'."""

    def __repr__(self):
        return '<undefined>'


UNDEF = _Undef()


# --------------------------------------------------------------------------
# runtime conversion ops (reference: convert_operators.convert_ifelse/...)
# --------------------------------------------------------------------------

def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_traced(x):
    return isinstance(_unwrap(x), jax.core.Tracer)


def _to_py_bool(pred):
    p = _unwrap(pred)
    if isinstance(p, (jax.Array, np.ndarray, np.generic)):
        if p.size != 1:
            raise Dy2StaticError(
                f'condition must be a scalar tensor, got shape {p.shape}')
        return bool(np.asarray(p).reshape(()))
    return bool(p)     # plain Python truthiness (lists, None, ints, ...)


def _check_bound(names, values, stmt):
    for n, v in zip(names, values):
        if v is UNDEF:
            raise Dy2StaticError(
                f"variable '{n}' is used after a tensor-dependent {stmt} "
                f"but is not bound before it (and, for if/else, not in "
                f"both branches). Initialize '{n}' before the {stmt} so "
                f"both paths produce the same variables.")


def convert_ifelse(pred, true_fn, false_fn, names, init_vals,
                   out_names=None):
    """if/else on ``pred``: lax.cond when traced, plain Python otherwise.

    ``names``/``init_vals``: the branch fns' parameter vars (inputs — every
    local either branch reads or rebinds, so outer values flow in even when
    the branch's own rebinding would shadow them). ``out_names``: the
    subset the branches RETURN (default: all) — a return-lowered terminal
    if passes the full modified set in but only the result carrier out,
    since nothing else is live after it."""
    if out_names is None:
        out_names = names
    if not _is_traced(pred):
        return true_fn(*init_vals) if _to_py_bool(pred) else \
            false_fn(*init_vals)
    # vars unbound BEFORE the if are fine as long as both branches bind
    # them (checked on the branch outputs); they ride the closure, not the
    # lax.cond operands, since UNDEF is not a jax type
    bound_idx = [i for i, v in enumerate(init_vals) if v is not UNDEF]
    u_init = tuple(_unwrap(init_vals[i]) for i in bound_idx)

    def _branch(fn):
        def run(u_vals):
            full = list(init_vals)
            for j, i in enumerate(bound_idx):
                full[i] = (Tensor(u_vals[j])
                           if isinstance(init_vals[i], Tensor) else u_vals[j])
            outs = fn(*full)
            _check_bound(out_names, outs, 'if/else')
            return tuple(_unwrap(o) for o in outs)
        return run

    try:
        outs = jax.lax.cond(_unwrap(pred), _branch(true_fn),
                            _branch(false_fn), u_init)
    except TypeError as e:
        raise Dy2StaticError(
            f'the two branches of a tensor-dependent if/else must produce '
            f'matching shapes/dtypes for {out_names}; ({e})') from e
    return tuple(Tensor(o) if isinstance(o, (jax.Array, jax.core.Tracer))
                 else o for o in outs)


def convert_while(cond_fn, body_fn, names, init_vals):
    """while loop: lax.while_loop when the condition traces, else Python."""
    first = cond_fn(*init_vals)
    if not _is_traced(first) and not any(_is_traced(v) for v in init_vals):
        # reuse `first` for the first test: re-evaluating would double any
        # side effects in the condition expression
        vals = tuple(init_vals)
        cont = first
        while True:
            if _is_traced(cont):
                # tensor-ness entered THROUGH the body (e.g. a traced
                # break/loop-return flag in an otherwise-python loop, with
                # no traced carrier at entry): continue as a lax loop from
                # the current state. The condition is re-evaluated once on
                # re-entry (condition side effects would double — same
                # caveat as the first-test reuse above).
                return convert_while(cond_fn, body_fn, names, vals)
            if not _to_py_bool(cont):
                return vals
            vals = tuple(body_fn(*vals))
            cont = cond_fn(*vals)

    _check_bound(names, init_vals, 'while')
    u_init = tuple(_unwrap(v) for v in init_vals)

    def rewrap(u_vals):
        return tuple(Tensor(u) if isinstance(orig, Tensor) else u
                     for orig, u in zip(init_vals, u_vals))

    def u_cond(u_vals):
        return _unwrap(cond_fn(*rewrap(u_vals)))

    def u_body(u_vals):
        outs = body_fn(*rewrap(u_vals))
        _check_bound(names, outs, 'while')
        return tuple(_unwrap(o) for o in outs)

    try:
        outs = jax.lax.while_loop(u_cond, u_body, u_init)
    except TypeError as e:
        raise Dy2StaticError(
            f'loop variables {names} of a tensor-dependent while must keep '
            f'the same shape/dtype every iteration; ({e})') from e
    return tuple(Tensor(o) if isinstance(o, (jax.Array, jax.core.Tracer))
                 else o for o in outs)


def convert_for_range(start, stop, step, body_fn, names, init_vals,
                      tgt_init=UNDEF):
    """``for <t> in range(...)``: lax.while_loop when any bound traces,
    exact Python iteration otherwise. Returns (final_target, *final_vars);
    ``tgt_init`` is the target's pre-loop value so a zero-trip loop leaves
    it untouched (or unbound), exactly like Python.

    Traced caveats (documented): after a zero-trip traced loop the target
    holds ``start - step`` rather than being unbound — data-dependent trip
    counts cannot leave a variable undefined in a static graph — and a
    traced ``step == 0`` yields zero iterations instead of Python's
    ``ValueError`` (a compiled graph cannot raise data-dependently).
    """
    traced = any(_is_traced(v) for v in (start, stop, step))
    if not traced:
        t = tgt_init
        vals = tuple(init_vals)
        for i in range(int(_unwrap(start)), int(_unwrap(stop)),
                       int(_unwrap(step))):
            t = i
            vals = tuple(body_fn(i, *vals))
        return (t,) + vals

    _check_bound(names, init_vals, 'for')
    u_start = jnp.asarray(_unwrap(start))
    u_stop = jnp.asarray(_unwrap(stop))
    u_step = jnp.asarray(_unwrap(step))

    def rewrap(u_vals):
        return tuple(Tensor(u) if isinstance(orig, Tensor) else u
                     for orig, u in zip(init_vals, u_vals))

    def u_cond(carry):
        i, _ = carry
        # step==0 must terminate (zero-trip), not spin forever
        return jnp.where(u_step > 0, i < u_stop,
                         (u_step < 0) & (i > u_stop))

    def u_body(carry):
        i, u_vals = carry
        outs = body_fn(Tensor(i), *rewrap(u_vals))
        _check_bound(names, outs, 'for')
        return i + u_step, tuple(_unwrap(o) for o in outs)

    try:
        i_fin, outs = jax.lax.while_loop(
            u_cond, u_body, (u_start, tuple(_unwrap(v) for v in init_vals)))
    except TypeError as e:
        raise Dy2StaticError(
            f'loop variables {names} of a tensor-range for must keep the '
            f'same shape/dtype every iteration; ({e})') from e
    return (Tensor(i_fin - u_step),) + tuple(
        Tensor(o) if isinstance(o, (jax.Array, jax.core.Tracer)) else o
        for o in outs)


def logical_and(lhs, rhs_thunk):
    """``a and b``. Traced lhs: jnp.logical_and (both sides evaluated —
    pure under trace). Plain lhs: exact Python semantics — short-circuit,
    operand (not bool) returned; a traced rhs simply passes through, which
    is what Python's `and` does too."""
    if _is_traced(lhs):
        return Tensor(jnp.logical_and(_unwrap(lhs), _unwrap(rhs_thunk())))
    return rhs_thunk() if _to_py_bool(lhs) else lhs


def logical_or(lhs, rhs_thunk):
    if _is_traced(lhs):
        return Tensor(jnp.logical_or(_unwrap(lhs), _unwrap(rhs_thunk())))
    return lhs if _to_py_bool(lhs) else rhs_thunk()


def logical_not(x):
    if _is_traced(x):
        return Tensor(jnp.logical_not(_unwrap(x)))
    return not _to_py_bool(x)


def loop_cond(idx, stop, step):
    """range-style continuation test handling negative steps, traced or
    plain (used by the while-form a `for range` with break lowers to).
    step == 0 matches range(): ValueError untraced, zero-trip traced (a
    compiled graph cannot raise data-dependently)."""
    ui, us, ust = _unwrap(idx), _unwrap(stop), _unwrap(step)
    if _is_traced(ui) or _is_traced(us) or _is_traced(ust):
        return jnp.where(jnp.asarray(ust) > 0,
                         jnp.asarray(ui) < jnp.asarray(us),
                         (jnp.asarray(ust) < 0) &
                         (jnp.asarray(ui) > jnp.asarray(us)))
    if ust == 0:
        raise ValueError('range() arg 3 must not be zero')
    return ui < us if ust > 0 else ui > us


def unsupported_guard(pred, reason):
    """Evaluated on conditions we could not rewrite: plain Python passes
    through untouched; a traced condition gets an actionable error."""
    if _is_traced(pred):
        raise Dy2StaticError(
            f'tensor-dependent control flow not convertible: {reason}. '
            f'Refactor so the branch/loop body only rebinds local '
            f'variables (no return/break/continue escaping it, no '
            f'attribute or subscript stores).')
    return pred


# --------------------------------------------------------------------------
# static analysis
# --------------------------------------------------------------------------

_INNER_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                 ast.ClassDef, ast.ListComp, ast.SetComp, ast.DictComp,
                 ast.GeneratorExp)


class _BodyInfo(ast.NodeVisitor):
    """Names bound by a statement list + escape/store-form diagnostics."""

    def __init__(self):
        self.assigned = set()
        self.complex_store = False     # a.b = / a[i] = inside the body
        self.escapes = False           # return, or break/continue that would
        self._loop_depth = 0           # leave the analyzed region

    def run(self, stmts):
        for s in stmts:
            self.visit(s)
        return self

    def _target(self, t):
        if isinstance(t, ast.Name):
            self.assigned.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)
        elif isinstance(t, ast.Starred):
            self._target(t.value)
        else:                          # Attribute / Subscript store
            self.complex_store = True

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
            self.visit(node.value)

    def visit_For(self, node):
        self._target(node.target)
        self._loop_depth += 1
        for s in node.body + node.orelse:
            self.visit(s)
        self._loop_depth -= 1

    def visit_While(self, node):
        self._loop_depth += 1
        for s in node.body + node.orelse:
            self.visit(s)
        self._loop_depth -= 1

    def visit_With(self, node):
        for item in node.items:
            if item.optional_vars is not None:
                self._target(item.optional_vars)
        for s in node.body:
            self.visit(s)

    def visit_Return(self, node):
        self.escapes = True

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.escapes = True

    def visit_Continue(self, node):
        if self._loop_depth == 0:
            self.escapes = True

    def generic_visit(self, node):
        if isinstance(node, _INNER_SCOPES):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.assigned.add(node.name)
            return                     # inner scope: bindings don't leak
        super().generic_visit(node)


def _mods_of(*stmt_lists):
    """User-visible names rebound by the statement lists, or None when the
    region cannot be converted (escaping control flow / complex stores)."""
    names = set()
    for stmts in stmt_lists:
        info = _BodyInfo().run(stmts)
        if info.escapes or info.complex_store:
            return None
        names |= info.assigned
    # generated names are internal EXCEPT the break/continue flags, the
    # while-form loop index, the return-lowering result carrier, and the
    # loop-return flags — those are genuine branch/loop-carried state
    keep = (f'{_GEN_PREFIX}brk', f'{_GEN_PREFIX}cont', f'{_GEN_PREFIX}idx',
            f'{_GEN_PREFIX}rv', f'{_GEN_PREFIX}lr', _ATTR_PREFIX)
    return sorted(n for n in names
                  if not n.startswith(_GEN_PREFIX) or n.startswith(keep))


# --------------------------------------------------------------------------
# AST rewriting
# --------------------------------------------------------------------------

def _load(name):
    return ast.Name(id=name, ctx=ast.Load())


def _store(name):
    return ast.Name(id=name, ctx=ast.Store())


def _rt_call(attr, args):
    return ast.Call(
        func=ast.Attribute(value=_load(_RT_NAME), attr=attr, ctx=ast.Load()),
        args=args, keywords=[])


def _sentinel_reads(mods, uid):
    """try: _pt_inK = v / except NameError: _pt_inK = UNDEF — one per var."""
    stmts, names = [], []
    for i, v in enumerate(mods):
        tmp = f'{_GEN_PREFIX}in{i}_{uid}'
        names.append(tmp)
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[_store(tmp)], value=_load(v))],
            handlers=[ast.ExceptHandler(
                type=_load('NameError'), name=None,
                body=[ast.Assign(
                    targets=[_store(tmp)],
                    value=ast.Attribute(value=_load(_RT_NAME), attr='UNDEF',
                                        ctx=ast.Load()))])],
            orelse=[], finalbody=[]))
    return stmts, names


def _func_def(name, params, body, returns):
    return ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[], kwonlyargs=[], kw_defaults=[],
                           defaults=[],
                           args=[ast.arg(arg=p) for p in params]),
        body=body + [ast.Return(value=ast.Tuple(
            elts=[_load(r) for r in returns], ctx=ast.Load()))],
        decorator_list=[], type_params=[])


def _names_tuple(mods):
    return ast.Tuple(elts=[ast.Constant(value=m) for m in mods],
                     ctx=ast.Load())


def _undef_dels(mods):
    """`if v is UNDEF: del v` per var — restores exact Python semantics
    (later reads raise UnboundLocalError) when the taken non-traced branch
    left a variable unbound."""
    out = []
    for m in mods:
        out.append(ast.If(
            test=ast.Compare(
                left=_load(m), ops=[ast.Is()],
                comparators=[ast.Attribute(value=_load(_RT_NAME),
                                           attr='UNDEF', ctx=ast.Load())]),
            body=[ast.Delete(targets=[ast.Name(id=m, ctx=ast.Del())])],
            orelse=[]))
    return out


def _rewrite_boolops(expr):
    """Rewrite `a and b` / `a or b` / `not a` in a condition into the
    runtime logical converters (reference: convert_operators.convert_logical_
    and/or/not) — right operands wrapped in lambdas so Python-path
    short-circuiting is preserved exactly."""

    class BoolRw(ast.NodeTransformer):
        def visit_BoolOp(self, node):
            self.generic_visit(node)
            attr = ('logical_and' if isinstance(node.op, ast.And)
                    else 'logical_or')
            out = node.values[0]
            for rhs in node.values[1:]:
                thunk = ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                       kw_defaults=[], defaults=[]),
                    body=rhs)
                out = _rt_call(attr, [out, thunk])
            return out

        def visit_UnaryOp(self, node):
            self.generic_visit(node)
            if isinstance(node.op, ast.Not):
                return _rt_call('logical_not', [node.operand])
            return node

        def visit_Lambda(self, node):    # don't descend into inner scopes
            return node

    return BoolRw().visit(expr)


def _assign(name, value_node):
    return ast.Assign(targets=[_store(name)], value=value_node)


def _const(v):
    return ast.Constant(value=v)


class _LoopReturnLowering(ast.NodeTransformer):
    """``return`` inside a LOOP body (reference return_transformer.py's
    loop case). Lowered to flag + break + a post-loop re-emission:

        while c:                    _pt_lr1 = False
            if t: return x          while c:
        rest                 =>         if t: _pt_lr1 = True; break
                                    if _pt_lr1: return x
                                    rest

    Only the plain-bool FLAG is loop-carried — never the value — so no
    carrier of unknown shape/dtype needs synthesizing: the loop state at
    the break is exactly the state after the loop (the break/continue
    guards freeze the rest of the body), so re-evaluating the return
    expression post-loop yields the same value. Requirements that follow:
    the return expression must be pure (it is evaluated once, after the
    loop — tensor expressions are; a side-effecting call would run at
    post-loop time), and its free variables must be bound on every path
    (the existing one-branch-binding rule). The emitted break/post-if then
    ride the existing break-flag and early-return lowerings, so a
    tensor-conditioned loop return becomes lax state with no new
    machinery. Runs INNERMOST-first: a return in a nested loop becomes
    flag+break there, and its post-loop ``if flag: return expr`` is
    rewritten again by the enclosing loop's pass."""

    def __init__(self):
        self._uid = 0
        self.applied = False

    _INNER_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)

    def _rewrite_returns(self, stmts, flags):
        """Replace direct returns (not inside nested loops or nested
        function/class scopes, which own their returns) with flag-set +
        break; record (flag, value_expr) into ``flags``."""
        out = []
        for st in stmts:
            if isinstance(st, ast.Return):
                self.applied = True
                self._uid += 1
                name = f'{_GEN_PREFIX}lr{self._uid}'
                flags.append((name, st.value or _const(None)))
                out.append(_assign(name, _const(True)))
                out.append(ast.Break())
                continue
            if not isinstance(st, (ast.For, ast.While) + self._INNER_SCOPES):
                for attr in ('body', 'orelse', 'finalbody'):
                    blk = getattr(st, attr, None)
                    if blk:
                        setattr(st, attr, self._rewrite_returns(blk, flags))
                for h in getattr(st, 'handlers', []) or []:
                    h.body = self._rewrite_returns(h.body, flags)
            out.append(st)
        return out

    def _lower_loop(self, node):
        self.generic_visit(node)           # innermost loops first
        flags = []
        node.body = self._rewrite_returns(node.body, flags)
        if not flags:
            return node
        pre = [_assign(n, _const(False)) for n, _ in flags]
        post = [ast.If(test=_load(n), body=[ast.Return(value=v)], orelse=[])
                for n, v in flags]
        return pre + [node] + post

    visit_For = _lower_loop
    visit_While = _lower_loop


class _ReturnLowering:
    """Early-``return`` support (reference: dygraph_to_static/
    return_transformer.py:1). A ``return`` inside an if-structure is lowered
    to single-exit form by pushing the statements AFTER the if into the
    else-continuation, so both arms of every tensor-convertible ``if`` bind
    one result carrier:

        if cond: return a          if cond: _pt_rv = a
        rest...             =>     else:    rest...; _pt_rv = b
        return b                   return _pt_rv

    This preserves exact Python semantics for non-tensor conditions (the
    restructured code runs the same statements in the same order) and makes
    tensor-conditioned early returns convertible to lax.cond. Continuations
    are deep-copied into each arm, so k sequential return-ifs cost O(2^k)
    code size — fine for the 1-3 early returns real code has. ``return``
    inside a LOOP body is handled by _LoopReturnLowering BEFORE this pass
    (flag + break + post-loop re-emission), so by the time this runs every
    return sits in straight-line/if code."""

    RV = f'{_GEN_PREFIX}rv'

    def __init__(self):
        self.applied = False

    def _has_return(self, stmts):
        for s in stmts or []:
            if isinstance(s, ast.Return):
                return True
            if isinstance(s, ast.If) and (self._has_return(s.body)
                                          or self._has_return(s.orelse)):
                return True
        return False

    def block(self, stmts, cont):
        """Rewrite one statement list; ``cont`` is the continuation that
        runs when control falls off the end (shared: deep-copied on use)."""
        import copy
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                self.applied = True
                out.append(_assign(self.RV,
                                   s.value or ast.Constant(value=None)))
                return out                     # rest of block unreachable
            if isinstance(s, ast.If) and (self._has_return(s.body)
                                          or self._has_return(s.orelse)):
                self.applied = True
                new_cont = stmts[i + 1:] + cont
                s.body = self.block(s.body, new_cont)
                s.orelse = self.block(s.orelse or [], new_cont)
                # terminal if: every path ends by binding the carrier, and
                # ONLY the carrier is live afterwards — the converter then
                # need not require branch-local temps bound in both arms
                s._pt_return_exit = True
                out.append(s)
                return out
            out.append(s)
        if cont:
            return out + self.block(copy.deepcopy(cont), [])
        out.append(_assign(self.RV, ast.Constant(value=None)))
        return out

    def run(self, fdef):
        needs = any(isinstance(s, ast.If) and (self._has_return(s.body)
                                               or self._has_return(s.orelse))
                    for s in fdef.body)
        if not needs:
            return False                   # no return under an if: no-op
        fdef.body = self.block(fdef.body, [])
        fdef.body.append(ast.Return(value=_load(self.RV)))
        return self.applied


class _BreakContinueTransformer(ast.NodeTransformer):
    """Lower ``break``/``continue`` into flag variables + guards BEFORE
    control-flow conversion (reference:
    dygraph_to_static/break_continue_transformer.py).

    The rewrite preserves plain-Python semantics exactly — flags are
    ordinary bools and the guards replicate the skipped control flow — so
    when a flag is set under a TENSOR condition, the main transformer's
    if/while conversion turns the flags into loop-carried lax values with
    no further special-casing. A ``for range`` containing ``break`` lowers
    to its while-form first so the flag can terminate the loop.
    """

    def __init__(self):
        self._uid = 0
        self.hoisted = []    # (name, default) pre-bound at function top so
        #                      enclosing converted constructs always see the
        #                      flags/index bound (no internal-name leaks)

    def _next(self):
        self._uid += 1
        return self._uid

    @staticmethod
    def _block_has_bc(stmts):
        """break/continue binding to THIS loop (don't descend into inner
        loops, which own their own break/continue)."""

        def scan(body):
            for st in body:
                if isinstance(st, (ast.Break, ast.Continue)):
                    return True
                if isinstance(st, (ast.For, ast.While)):
                    # the inner loop owns break/continue in its BODY, but its
                    # for/while-else block binds to THIS loop
                    if scan(st.orelse or []):
                        return True
                    continue
                for attr in ('body', 'orelse', 'finalbody'):
                    if scan(getattr(st, attr, []) or []):
                        return True
                for h in getattr(st, 'handlers', []) or []:
                    if scan(h.body):       # except-blocks can break/continue
                        return True
            return False
        return scan(stmts)

    def _guard(self, stmts, fb, fc):
        """Rewrite one block: break/continue become flag sets; everything
        after a statement that MAY have set a flag runs under
        ``if not (fb or fc)``. Returns (new_stmts, may_set_flag)."""
        out = []
        for i, st in enumerate(stmts):
            if isinstance(st, ast.Break):
                out.append(_assign(fb, _const(True)))
                return out, True       # rest of block is unreachable
            if isinstance(st, ast.Continue):
                out.append(_assign(fc, _const(True)))
                return out, True
            found = False
            if isinstance(st, (ast.For, ast.While)):
                # inner loop owns its break/continue — but its else-block
                # runs in THIS loop's scope
                if st.orelse:
                    new, f = self._guard(st.orelse, fb, fc)
                    st.orelse = new
                    found = found or f
            elif isinstance(st, (ast.If, ast.With, ast.Try)):
                for attr in ('body', 'orelse', 'finalbody'):
                    blk = getattr(st, attr, None)
                    if blk:
                        new, f = self._guard(blk, fb, fc)
                        setattr(st, attr, new)
                        found = found or f
                for h in getattr(st, 'handlers', []) or []:
                    new, f = self._guard(h.body, fb, fc)
                    h.body = new
                    found = found or f
            out.append(st)
            if found:
                rest, _ = self._guard(stmts[i + 1:], fb, fc)
                if rest:
                    cond = ast.UnaryOp(op=ast.Not(), operand=ast.BoolOp(
                        op=ast.Or(), values=[_load(fb), _load(fc)]))
                    out.append(ast.If(test=cond, body=rest, orelse=[]))
                return out, True
        return out, False

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or not self._block_has_bc(node.body):
            return node
        uid = self._next()
        fb, fc = f'{_GEN_PREFIX}brk{uid}', f'{_GEN_PREFIX}cont{uid}'
        self.hoisted += [(fb, False), (fc, False)]
        body, _ = self._guard(node.body, fb, fc)
        node.body = [_assign(fc, _const(False))] + body
        node.test = ast.BoolOp(op=ast.And(), values=[
            node.test, ast.UnaryOp(op=ast.Not(), operand=_load(fb))])
        # both flags pre-bound: they are loop-carried state for convert_while
        return [_assign(fb, _const(False)), _assign(fc, _const(False)), node]

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or not self._block_has_bc(node.body):
            return node
        if not (_is_range_for(node) and isinstance(node.target, ast.Name)):
            # plain-iterable for: continue lowers with guards alone (the
            # iteration count is unchanged); break over a Python iterable
            # keeps Python semantics untouched (a traced break condition
            # then raises via the If conversion's unsupported_guard)
            if not any(isinstance(s, ast.Break) for s in ast.walk(ast.Module(
                    body=node.body, type_ignores=[]))):
                uid = self._next()
                fb, fc = f'{_GEN_PREFIX}brk{uid}', f'{_GEN_PREFIX}cont{uid}'
                self.hoisted += [(fb, False), (fc, False)]
                body, _ = self._guard(node.body, fb, fc)
                node.body = ([_assign(fb, _const(False)),
                              _assign(fc, _const(False))] + body)
            return node
        uid = self._next()
        fb, fc = f'{_GEN_PREFIX}brk{uid}', f'{_GEN_PREFIX}cont{uid}'
        idx = f'{_GEN_PREFIX}idx{uid}'
        stopn, stepn = f'{_GEN_PREFIX}stop{uid}', f'{_GEN_PREFIX}step{uid}'
        self.hoisted += [(fb, False), (fc, False), (idx, 0)]
        a = node.iter.args
        if len(a) == 1:
            start, stop, step = _const(0), a[0], _const(1)
        elif len(a) == 2:
            start, stop, step = a[0], a[1], _const(1)
        else:
            start, stop, step = a
        body, _ = self._guard(node.body, fb, fc)
        loop = ast.While(
            test=ast.BoolOp(op=ast.And(), values=[
                _rt_call('loop_cond', [_load(idx), _load(stopn),
                                       _load(stepn)]),
                ast.UnaryOp(op=ast.Not(), operand=_load(fb))]),
            body=[_assign(node.target.id, _load(idx)),
                  _assign(idx, ast.BinOp(left=_load(idx), op=ast.Add(),
                                         right=_load(stepn))),
                  _assign(fc, _const(False))] + body,
            orelse=[])
        # pre-bind the loop target (= start) ONLY when it is unbound, so it
        # is valid while_loop carry state without clobbering a prior
        # binding on a zero-trip plain-Python loop; a zero-trip traced loop
        # leaves it at start — the materialization any traced program needs
        bind_now = _assign(node.target.id, _load(idx))
        undef_attr = ast.Attribute(value=_load(_RT_NAME), attr='UNDEF',
                                   ctx=ast.Load())
        tgt_bind = ast.Try(
            # bound-to-UNDEF counts as unbound: an enclosing converted
            # construct's sentinel may have handed us the UNDEF marker
            body=[ast.If(test=ast.Compare(left=_load(node.target.id),
                                          ops=[ast.Is()],
                                          comparators=[undef_attr]),
                         body=[bind_now], orelse=[])],
            handlers=[ast.ExceptHandler(type=_load('NameError'), name=None,
                                        body=[bind_now])],
            orelse=[], finalbody=[])
        return [_assign(stopn, stop), _assign(stepn, step),
                _assign(idx, start), tgt_bind,
                _assign(fb, _const(False)), _assign(fc, _const(False)), loop]


def _slot_key(node):
    """Canonical identity of an attribute/subscript slot expression. Only
    the OUTERMOST node's ctx differs between a store target and a read, so
    dumping the (always-Load) inner parts directly is ctx-insensitive
    without any copying."""
    if isinstance(node, ast.Attribute):
        return f'{ast.dump(node.value)}.{node.attr}'
    return f'{ast.dump(node.value)}[{ast.dump(node.slice)}]'


class _SlotRewriter(ast.NodeTransformer):
    """Replace every read/write of the planned slots with their temp name."""

    def __init__(self, plan):
        self.plan = plan               # slot key -> gen name

    def _swap(self, node):
        if isinstance(node.ctx, ast.Del):
            return None        # `del slot` is never lowered (plan excludes)
        gen = self.plan.get(_slot_key(node))
        if gen is None:
            return None
        return ast.copy_location(
            ast.Name(id=gen, ctx=type(node.ctx)()), node)

    def visit_Attribute(self, node):
        got = self._swap(node)
        if got is not None:
            return got
        self.generic_visit(node)
        return node

    def visit_Subscript(self, node):
        got = self._swap(node)
        if got is not None:
            return got
        self.generic_visit(node)
        return node

    def visit_FunctionDef(self, node):      # inner scopes untouched
        return node
    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node


class _ComplexStoreLowering(ast.NodeTransformer):
    """Attribute/subscript-store support inside convertible control flow
    (VERDICT r3 'Next' #6, second half; the reference's dygraph_to_static
    handles these through variable-scope snapshots).

    ``self.n = self.n + 1`` inside a tensor-conditioned branch/loop is
    lowered by LOCALIZING the slot: read it into a temp before the
    construct, rewrite every read/write of that slot inside the construct
    to the temp (so it becomes ordinary branch/loop-carried state the
    if/while converters already handle), and write the temp back after:

        _pt_attrN = self.n            # UNDEF if the slot doesn't exist yet
        <construct, with self.n -> _pt_attrN>
        if _pt_attrN is not UNDEF: self.n = _pt_attrN

    Equivalent under plain-Python execution for direct slot access (same
    reads through the literal expression, same final store). KNOWN
    DIVERGENCES (shared with the reference's scope-snapshot approach):
    reads through an ALIAS of the slot inside the construct (a method call
    that reads self.n, passing the dict to a helper) see the pre-construct
    value until the write-back; property setters fire once at write-back,
    not per store; an exception escaping the construct skips the
    write-back. Unsafe cases stay on the unsupported-construct error: a
    slot whose index/object names are rebound inside the construct, a slot
    also stored inside a NESTED python loop (per-iteration slot identity
    can change there), or a `del` of the slot."""

    def __init__(self):
        self._uid = 0

    def _gen(self):
        self._uid += 1
        return f'{_ATTR_PREFIX}{self._uid}'

    # ---- collection ------------------------------------------------------
    @staticmethod
    def _targets_of(s):
        if isinstance(s, ast.Assign):
            return s.targets
        if isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            return [s.target]
        return []

    def _scan(self, stmts, shallow, in_loop, loop_stored):
        for s in stmts or []:
            for t in self._targets_of(s):
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    key = _slot_key(t)
                    if in_loop:
                        loop_stored.add(key)
                    else:
                        shallow.setdefault(key, t)
            if isinstance(s, ast.Delete):
                # `del slot` cannot be expressed by the write-back: any
                # deleted slot is unsafe to localize at this level
                for t in s.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        loop_stored.add(_slot_key(t))
            if isinstance(s, (_INNER_SCOPES)):
                continue
            nested_loop = in_loop or isinstance(s, (ast.For, ast.While))
            for attr in ('body', 'orelse', 'finalbody'):
                self._scan(getattr(s, attr, None), shallow, nested_loop,
                           loop_stored)
            for h in getattr(s, 'handlers', []) or []:
                self._scan(h.body, shallow, nested_loop, loop_stored)

    def _lower(self, node, blocks):
        shallow, loop_stored = {}, set()
        for blk in blocks:
            self._scan(blk, shallow, False, loop_stored)
        if not shallow:
            return node
        assigned = set()
        for blk in blocks:
            assigned |= _BodyInfo().run(blk).assigned
        if isinstance(node, ast.For):
            assigned |= _BodyInfo().run([node]).assigned  # the loop target
        plan = {}
        for key, t in shallow.items():
            if key in loop_stored:
                continue                 # also stored per-iteration: unsafe
            slot_names = {n.id for sub in ([t.value] + (
                [t.slice] if isinstance(t, ast.Subscript) else []))
                for n in ast.walk(sub) if isinstance(n, ast.Name)}
            if slot_names & assigned:
                continue                 # slot identity changes inside
            plan[key] = (self._gen(), t)
        if not plan:
            return node
        rw = _SlotRewriter({k: g for k, (g, _) in plan.items()})
        for blk in blocks:
            blk[:] = [rw.visit(s) for s in blk]
        if isinstance(node, (ast.While, ast.For)):
            node.test = rw.visit(node.test) if isinstance(
                node, ast.While) else node.test
        import copy
        pre, post = [], []
        undef = ast.Attribute(value=_load(_RT_NAME), attr='UNDEF',
                              ctx=ast.Load())
        for key, (gen, t) in plan.items():
            read = copy.deepcopy(t)
            for sub in ast.walk(read):
                if isinstance(sub, (ast.Name, ast.Attribute, ast.Subscript)):
                    sub.ctx = ast.Load()
            pre.append(ast.Try(
                body=[_assign(gen, read)],
                handlers=[ast.ExceptHandler(
                    type=_load('Exception'), name=None,
                    body=[_assign(gen, copy.deepcopy(undef))])],
                orelse=[], finalbody=[]))
            store_t = copy.deepcopy(t)
            store_t.ctx = ast.Store()
            # NameError-tolerant: the converters del UNDEF-valued temps
            # after an untaken python branch (unbound-semantics restore)
            post.append(ast.Try(
                body=[ast.If(
                    test=ast.Compare(left=_load(gen), ops=[ast.IsNot()],
                                     comparators=[copy.deepcopy(undef)]),
                    body=[ast.Assign(targets=[store_t], value=_load(gen))],
                    orelse=[])],
                handlers=[ast.ExceptHandler(
                    type=_load('NameError'), name=None,
                    body=[ast.Pass()])],
                orelse=[], finalbody=[]))
        return pre + [node] + post

    def visit_If(self, node):
        self.generic_visit(node)
        return self._lower(node, [node.body, node.orelse])

    def visit_While(self, node):
        self.generic_visit(node)
        return self._lower(node, [node.body, node.orelse])

    def visit_For(self, node):
        self.generic_visit(node)
        return self._lower(node, [node.body, node.orelse])

    def visit_FunctionDef(self, node):
        # only the OUTER function being converted: process its statements
        # but do not descend into nested defs (fresh scopes)
        if getattr(self, '_entered', False):
            return node
        self._entered = True
        new = []
        for s in node.body:
            r = self.visit(s)
            new.extend(r if isinstance(r, list) else [r])
        node.body = new
        return node
    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return node


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _next(self):
        self._uid += 1
        return self._uid

    # -- if/else ---------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        node.test = _rewrite_boolops(node.test)
        mods = _mods_of(node.body, node.orelse)
        out_mods = None
        if mods and getattr(node, '_pt_return_exit', False):
            # return-lowered terminal if: live after it are only the result
            # carrier and the localized attribute/subscript-slot temps
            # (their function-end write-back is a real side effect), so
            # only those are RETURNED/matched across branches — but the
            # full modified set still flows IN as branch-fn params (a
            # branch that reads x then rebinds it would otherwise shadow
            # the outer x into an unbound local)
            out_mods = ([_ReturnLowering.RV]
                        + [m for m in mods if m.startswith(_ATTR_PREFIX)])
        if mods is None or not mods:
            # not convertible (or pure side-effect): keep Python `if`, but
            # make a traced condition fail with a clear message
            reason = ('branch contains return/break/continue or attribute/'
                      'subscript stores' if mods is None
                      else 'branch rebinds no local variables')
            node.test = _rt_call('unsupported_guard',
                                 [node.test, ast.Constant(value=reason)])
            return node
        uid = self._next()
        rets = out_mods or mods
        tname, fname = f'{_GEN_PREFIX}t_{uid}', f'{_GEN_PREFIX}f_{uid}'
        sent, tmp_names = _sentinel_reads(mods, uid)
        call = ast.Assign(
            targets=[ast.Tuple(elts=[_store(m) for m in rets],
                               ctx=ast.Store())],
            value=_rt_call('convert_ifelse', [
                _load(f'{_GEN_PREFIX}c_{uid}'), _load(tname), _load(fname),
                _names_tuple(mods),
                ast.Tuple(elts=[_load(t) for t in tmp_names],
                          ctx=ast.Load()),
                _names_tuple(rets)]))
        return [
            ast.Assign(targets=[_store(f'{_GEN_PREFIX}c_{uid}')],
                       value=node.test),
            _func_def(tname, mods, node.body, rets),
            _func_def(fname, mods, node.orelse or [ast.Pass()], rets),
            *sent, call, *_undef_dels(rets),
        ]

    # -- while -----------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        node.test = _rewrite_boolops(node.test)
        mods = _mods_of(node.body)
        if mods is None or not mods or node.orelse:
            reason = ('while has an else clause' if node.orelse else
                      'body contains return/break/continue or attribute/'
                      'subscript stores' if mods is None
                      else 'body rebinds no local variables')
            node.test = _rt_call('unsupported_guard',
                                 [node.test, ast.Constant(value=reason)])
            return node
        uid = self._next()
        cname, bname = f'{_GEN_PREFIX}wc_{uid}', f'{_GEN_PREFIX}wb_{uid}'
        sent, tmp_names = _sentinel_reads(mods, uid)
        cond_fn = ast.FunctionDef(
            name=cname,
            args=ast.arguments(posonlyargs=[], kwonlyargs=[], kw_defaults=[],
                               defaults=[],
                               args=[ast.arg(arg=p) for p in mods]),
            body=[ast.Return(value=node.test)], decorator_list=[],
            type_params=[])
        call = ast.Assign(
            targets=[ast.Tuple(elts=[_store(m) for m in mods],
                               ctx=ast.Store())],
            value=_rt_call('convert_while', [
                _load(cname), _load(bname), _names_tuple(mods),
                ast.Tuple(elts=[_load(t) for t in tmp_names],
                          ctx=ast.Load())]))
        return [cond_fn, _func_def(bname, mods, node.body, mods),
                *sent, call, *_undef_dels(mods)]

    # -- for i in range(...) --------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        if not _is_range_for(node):
            # non-range iterables unroll under tracing (plain Python) —
            # leave untouched
            return node
        mods = _mods_of(node.body)
        if not isinstance(node.target, ast.Name) or node.orelse \
                or mods is None or not mods:
            # not convertible: keep Python semantics, but a TRACED bound
            # gets an actionable error instead of jax's concretization one
            reason = ('for has an else clause' if node.orelse else
                      'loop target is not a simple name'
                      if not isinstance(node.target, ast.Name) else
                      'body contains return/break/continue or attribute/'
                      'subscript stores' if mods is None
                      else 'body rebinds no local variables')
            node.iter.args = [
                _rt_call('unsupported_guard', [a, ast.Constant(value=reason)])
                for a in node.iter.args]
            return node
        uid = self._next()
        a = node.iter.args
        if len(a) == 1:
            start, stop, step = ast.Constant(value=0), a[0], \
                ast.Constant(value=1)
        elif len(a) == 2:
            start, stop, step = a[0], a[1], ast.Constant(value=1)
        else:
            start, stop, step = a
        tgt = node.target.id
        bname = f'{_GEN_PREFIX}rb_{uid}'
        body_fn = _func_def(bname, [tgt] + mods, node.body, mods)
        # sentinel-read the target too: a zero-trip Python loop must leave
        # a pre-existing binding untouched (and an absent one absent)
        sent, tmp_names = _sentinel_reads(mods + [tgt], uid)
        call = ast.Assign(
            targets=[ast.Tuple(elts=[_store(tgt)] + [_store(m) for m in mods],
                               ctx=ast.Store())],
            value=_rt_call('convert_for_range', [
                start, stop, step, _load(bname), _names_tuple(mods),
                ast.Tuple(elts=[_load(t) for t in tmp_names[:-1]],
                          ctx=ast.Load()),
                _load(tmp_names[-1])]))
        return [body_fn, *sent, call, *_undef_dels([tgt] + mods)]


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------

def _is_range_for(node):
    return (isinstance(node, ast.For)
            and isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id == 'range'
            and not node.iter.keywords
            and 1 <= len(node.iter.args) <= 3)


def _has_control_flow(tree):
    """Only rewrite functions we might actually convert: if/while, or a
    range() for. A function with only plain-iterable fors is returned
    untouched — re-exec'ing it would needlessly snapshot its closure and
    strip stacked decorators."""
    return any(isinstance(n, (ast.If, ast.While)) or _is_range_for(n)
               for n in ast.walk(tree))


def convert_control_flow(fn):
    """Rewrite tensor-conditioned if/while in ``fn`` (best effort).

    Returns ``fn`` unchanged when it has no control flow, its source is
    unavailable (C functions, REPL lambdas), or the rewrite fails — plain
    jax.jit tracing then applies, exactly as before.
    """
    bound_self = getattr(fn, '__self__', None)
    # method-like objects without __func__ (e.g. the StaticFunction bound
    # accessor) convert as themselves
    raw = getattr(fn, '__func__', fn) if bound_self is not None else fn
    try:
        src = textwrap.dedent(inspect.getsource(raw))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    if not _has_control_flow(fdef):
        return fn
    fdef.decorator_list = []           # avoid re-entering to_static on exec
    try:
        # loop-returns become flag+break+post-loop-return FIRST, so the
        # emitted pieces ride the break and early-return lowerings below
        _LoopReturnLowering().visit(fdef)
        ast.fix_missing_locations(tree)
        _ReturnLowering().run(fdef)
        bc = _BreakContinueTransformer()
        bc.visit(fdef)
        _ComplexStoreLowering().visit(fdef)
        # hoist flag/index defaults to the function top: enclosing converted
        # constructs then always see these generated names bound, so they
        # never surface in a user-facing unbound-variable error
        fdef.body = [_assign(n, _const(v)) for n, v in bc.hoisted] + fdef.body
        _ControlFlowTransformer().visit(fdef)
        ast.fix_missing_locations(tree)
        code = compile(tree, filename=f'<dy2static:{raw.__name__}>',
                       mode='exec')
        # globals DELEGATE to the live module namespace so helpers defined
        # (or monkeypatched) after decoration still resolve; only the
        # runtime alias and closure snapshot shadow it
        glb = _LiveGlobals(raw.__globals__)
        glb[_RT_NAME] = _runtime_namespace()
        if raw.__closure__:
            # re-exec'ing a def cannot rebuild cells: snapshot the captured
            # values (static capture — documented limitation); an empty cell
            # (sibling defined later) aborts conversion via the fallback
            glb.update(zip(raw.__code__.co_freevars,
                           (c.cell_contents for c in raw.__closure__)))
        exec(code, glb)                # noqa: S102 — controlled source
        new_fn = functools.wraps(raw)(glb[raw.__name__])
    except Exception as e:             # noqa: BLE001 — never break tracing
        warnings.warn(f'dy2static: could not convert control flow in '
                      f'{raw.__name__} ({e}); falling back to plain tracing')
        return fn
    if bound_self is not None:
        return types.MethodType(new_fn, bound_self)
    return new_fn


class _LiveGlobals(dict):
    """exec-globals that fall through to the function's real module globals
    (CPython honors __missing__ for dict subclasses in LOAD_GLOBAL)."""

    def __init__(self, live):
        super().__init__()
        self['__builtins__'] = live.get('__builtins__', __builtins__)
        self._live = live

    def __missing__(self, key):
        return self._live[key]


class _runtime_namespace:
    UNDEF = UNDEF
    convert_ifelse = staticmethod(convert_ifelse)
    convert_while = staticmethod(convert_while)
    convert_for_range = staticmethod(convert_for_range)
    logical_and = staticmethod(logical_and)
    logical_or = staticmethod(logical_or)
    logical_not = staticmethod(logical_not)
    loop_cond = staticmethod(loop_cond)
    unsupported_guard = staticmethod(unsupported_guard)
