"""paddle.jit — dy2static. Reference: python/paddle/jit/ + fluid/dygraph/jit.py.

TPU-native: ``to_static`` doesn't rewrite Python AST into ProgramDesc like the
reference (python/paddle/fluid/dygraph/dygraph_to_static); it traces the
function through jax.jit — the jaxpr IS the static program, and XLA compiles
it for TPU. Differentiable: the compiled callable is registered on the eager
tape via jax.vjp, so ``loss.backward()`` crosses the jit boundary.
``jit.save``/``jit.load`` export params + StableHLO; the inference engine
(paddle_tpu.inference) AOT-compiles the loaded program.
"""
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer, functional_call, param_arrays, buffer_arrays
from ..static.input_spec import InputSpec
from ..tensor.random import rng_scope, next_key


class TracedLayer:
    pass


def _trace_state_clean():
    """True when no jax trace (jit/grad/vmap/export) is active. Private-API
    fast path with a tracer-scan-free conservative fallback."""
    try:
        from jax._src.core import trace_state_clean
        return trace_state_clean()
    except Exception:   # pragma: no cover — jax internals moved
        return True


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


class StaticFunction:
    """Compiled wrapper around a Python function / Layer.forward."""

    def __init__(self, function, input_spec=None):
        if not getattr(function, '_not_to_static', False):
            # dy2static pass: rewrite tensor-conditioned if/while into
            # lax.cond / lax.while_loop (no-op for control-flow-free fns)
            from .dy2static import convert_control_flow
            function = convert_control_flow(function)
        self._fn = function
        self._input_spec = input_spec
        self._layer = getattr(function, '__self__', None)
        self._cache = {}       # cache_key -> (jitted_pure, holder)

    def __get__(self, obj, objtype=None):
        # descriptor protocol: `@to_static` in a CLASS BODY (the reference
        # idiom) must bind `self` like a method — Layer.__call__ then
        # reaches __call__ with the instance first, and _bound_layer
        # routes through the layer path (r4b). A plain closure (not
        # functools.partial) so jit.save can still read the decoration
        # metadata off layer.forward.
        if obj is None:
            return self
        sf = self

        def bound(*args, **kwargs):
            return sf(obj, *args, **kwargs)
        bound.__self__ = obj
        bound._input_spec = self._input_spec
        bound._static_function = sf
        return bound

    def _cache_for(self, layer):
        # one class-level StaticFunction serves EVERY instance under
        # class-body decoration, and _build bakes the instance into the
        # compiled closure — so compiled entries must be per-instance
        # (review r4b: instance B silently ran A's trace). WeakKey: a
        # dropped instance must not pin its compiled programs.
        if layer is None:
            return self._cache
        import weakref
        if not hasattr(self, '_inst_caches'):
            self._inst_caches = weakref.WeakKeyDictionary()
        cache = self._inst_caches.get(layer)
        if cache is None:
            cache = self._inst_caches[layer] = {}
        return cache

    def _bound_layer(self, args):
        if self._layer is not None:
            return self._layer, args
        if args and isinstance(args[0], Layer):
            return args[0], args[1:]
        return None, args

    def _build(self, layer, training, tensor_like, static_ctx, kwargs):
        fn = self._fn
        if layer is not None and self._layer is None:
            fn = functools.partial(self._fn, layer)
        pnames = static_ctx['pnames']
        bnames = static_ctx['bnames']
        static_args = static_ctx['static_args']   # {pos: value}
        nargs = static_ctx['nargs']
        holder = {'treedef': None, 'n_out': 0}

        def pure(rng_key, buf_vals, *dyn):
            dyn_args = dyn[:len(tensor_like)]
            p_vals = dyn[len(tensor_like):]
            full_args = [None] * nargs
            for pos, v in static_args.items():
                full_args[pos] = v
            for i, idx in enumerate(tensor_like):
                full_args[idx] = dyn_args[i]
            with rng_scope(rng_key):
                if layer is not None:
                    pd = dict(zip(pnames, p_vals))
                    bd = dict(zip(bnames, buf_vals))
                    was = layer.training
                    for l in layer.sublayers(include_self=True):
                        l.training = training
                    try:
                        from ..nn.layer_base import functional_call_method
                        out, new_buf = functional_call_method(
                            layer, fn, pd, bd, *full_args, **kwargs)
                    finally:
                        for l in layer.sublayers(include_self=True):
                            l.training = was
                    new_buf_vals = [new_buf[n] for n in bnames]
                else:
                    targs = [Tensor(a) if isinstance(a, (jax.Array, jax.core.Tracer,
                                                         np.ndarray)) else a
                             for a in full_args]
                    from ..core.tensor import no_grad_ctx
                    with no_grad_ctx():
                        res = fn(*targs, **kwargs)
                    out = jax.tree_util.tree_map(
                        lambda x: x._value if isinstance(x, Tensor) else x, res,
                        is_leaf=lambda x: isinstance(x, Tensor))
                    new_buf_vals = []
            leaves, treedef = jax.tree_util.tree_flatten(out)
            holder['treedef'] = treedef
            holder['n_out'] = len(leaves)
            return tuple(leaves) + tuple(new_buf_vals)

        return jax.jit(pure), holder

    def __call__(self, *args, **kwargs):
        if not ProgramTranslator.enabled:
            # reference semantics: ProgramTranslator.enable(False) makes
            # @to_static functions run in plain dygraph (the converted fn
            # preserves eager behaviour exactly)
            return self._fn(*args, **kwargs)
        # Already inside an outer jax trace (jit.save export, a fused hapi
        # train step, dryrun pjit...): the inner jit+cache machinery is void
        # — everything is being traced anyway — and re-reading
        # layer.named_parameters() here would capture the outer trace's
        # substituted tracers into a cached closure (leaf-count corruption
        # at export). Run the converted function directly.
        if not _trace_state_clean():
            return self._fn(*args, **kwargs)
        layer, call_args = self._bound_layer(args)
        arg_arrays = [a._value if isinstance(a, Tensor) else a for a in call_args]
        tensor_like = tuple(i for i, a in enumerate(arg_arrays)
                            if isinstance(a, (jax.Array, np.ndarray, jax.core.Tracer)))
        static_args = {i: a for i, a in enumerate(arg_arrays) if i not in tensor_like}
        training = layer.training if layer is not None else False

        if layer is not None:
            named_p = list(layer.named_parameters())
            named_b = list(layer.named_buffers())
            pnames = [n for n, _ in named_p]
            bnames = [n for n, _ in named_b]
            params = [p for _, p in named_p]
            buffers = [b._value for _, b in named_b]
        else:
            pnames, bnames, params, buffers = [], [], [], []

        cache_key = (training, tensor_like, len(arg_arrays),
                     _hashable(static_args), _hashable(kwargs), tuple(pnames))
        cache = self._cache_for(layer)
        entry = cache.get(cache_key)
        if entry is None:
            static_ctx = {'pnames': pnames, 'bnames': bnames,
                          'static_args': static_args, 'nargs': len(arg_arrays)}
            entry = self._build(layer, training, tensor_like, static_ctx, kwargs)
            cache[cache_key] = entry
        jitted, holder = entry

        dyn_tensors = [call_args[i] if isinstance(call_args[i], Tensor)
                       else Tensor(jnp.asarray(arg_arrays[i])) for i in tensor_like]
        key = next_key()
        results = apply_op(jitted, Tensor(key), [Tensor(b) for b in buffers],
                           *dyn_tensors, *params)
        if not isinstance(results, (list, tuple)):
            results = (results,)
        n_out = holder['n_out']
        out_leaves = list(results[:n_out])
        new_bufs = results[n_out:]
        if layer is not None and training:
            for (n, b), nb in zip(layer.named_buffers(), new_bufs):
                b._replace_value(nb._value)
        return jax.tree_util.tree_unflatten(holder['treedef'], out_leaves)


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    def decorate(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward, input_spec)
            return fn
        sf = StaticFunction(fn, input_spec)
        functools.update_wrapper(sf, fn) if not isinstance(fn, functools.partial) else None
        return sf
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def _spec_to_example(spec):
    shape = [1 if (s is None or s == -1) else int(s) for s in spec.shape]
    return jnp.zeros(shape, spec.dtype)


def save(layer, path, input_spec=None, **configs):
    """Persist params + buffers + StableHLO of the traced forward.

    Mirrors the reference's jit.save (__model__ ProgramDesc + params,
    python/paddle/fluid/dygraph/jit.py:save); here the portable program
    format is StableHLO text, consumed by paddle_tpu.inference.Predictor.
    """
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    from ..framework_io import save as fsave
    if not isinstance(layer, Layer):
        # reference jit.save also accepts a @to_static FUNCTION: persist it
        # as a param-less program (StaticFunction or plain callable)
        return _save_function(layer, path, input_spec)
    fwd = layer.forward
    state = {'params': {n: np.asarray(p._value) for n, p in layer.named_parameters()},
             'buffers': {n: np.asarray(b._value) for n, b in layer.named_buffers()}}
    fsave(state, path + '.pdparams')
    if input_spec is None:
        input_spec = (getattr(fwd, '_input_spec', None) or
                      getattr(layer, '_input_spec', None))
    meta = {'class': type(layer).__name__}
    if input_spec is not None:
        specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
                 for s in input_spec]
        meta['input_spec'] = [{'shape': [(-1 if d is None else int(d)) for d in s.shape],
                               'dtype': str(np.dtype(s.dtype).name)} for s in specs]
        examples = [_spec_to_example(s) for s in specs]
        pd = {n: p._value for n, p in layer.named_parameters()}
        bd = {n: b._value for n, b in layer.named_buffers()}
        was_training = layer.training
        layer.eval()
        # a RAW layer with tensor control flow must trace through the
        # dy2static conversion exactly like the @to_static call path
        # (reference jit.save converts the forward too). The converted
        # forward is installed as an INSTANCE attribute for the trace so
        # layer.__call__ still runs forward pre/post hooks (weight_norm/
        # spectral_norm recompute weights in a pre-hook — bypassing
        # __call__ would bake stale weights into the export).
        import contextlib

        from .dy2static import convert_control_flow
        fwd_conv = convert_control_flow(layer.forward)

        @contextlib.contextmanager
        def converted_forward():
            had = 'forward' in layer.__dict__
            prev = layer.__dict__.get('forward')
            object.__setattr__(layer, 'forward', fwd_conv)
            try:
                yield
            finally:
                if had:
                    object.__setattr__(layer, 'forward', prev)
                else:
                    layer.__dict__.pop('forward', None)

        def infer_fn(*xs):
            with converted_forward():
                out, _ = functional_call(layer, pd, bd, *xs)
            return out

        def infer_fn_functional(params, buffers, *xs):
            with converted_forward():
                out, _ = functional_call(layer, params, buffers, *xs)
            return out
        try:
            _export_artifacts(infer_fn, infer_fn_functional, pd, bd, specs,
                              examples, path, meta)
        finally:
            if was_training:
                layer.train()
    import json
    with open(path + '.pdmodel', 'w') as f:
        json.dump(meta, f)


def _export_artifacts(infer_fn, infer_fn_functional, pd, bd, specs, examples,
                      path, meta):
    """Shared export machinery for Layer and function saves: StableHLO dump
    plus the standalone serialized program (jax.export) — the portable
    analogue of the reference's __model__ ProgramDesc, which the Predictor
    runs WITHOUT the Python object. Dims marked -1/None become symbolic so
    one artifact serves any size along those axes. Tried in order: one
    symbol per dynamic dim (fully independent), one shared symbol (programs
    that require equal dynamic dims, e.g. two inputs added together), then
    fully concrete example shapes. On total failure the cause lands in
    meta['export_error'] and any stale .pdexec from a prior save is removed.
    """
    lowered = jax.jit(infer_fn).lower(*examples)
    with open(path + '.stablehlo', 'w') as f:
        f.write(lowered.as_text())
    meta['exported'] = False
    meta['poly_batch'] = False
    from jax import export as jax_export
    p_struct = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), pd)
    b_struct = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), bd)

    def _sym_specs(shared):
        n_dyn = sum(1 for s in specs for d in s.shape
                    if d is None or d == -1)
        if n_dyn == 0:
            return None, False
        names = 'b' if shared else ', '.join(f'b{i}' for i in range(n_dyn))
        syms = list(jax_export.symbolic_shape(names))
        it = iter(syms * n_dyn if shared else syms)
        out = []
        for s in specs:
            dims = [next(it) if (d is None or d == -1) else int(d)
                    for d in s.shape]
            out.append(jax.ShapeDtypeStruct(tuple(dims), s.dtype))
        return out, True

    n_dyn_total = sum(1 for s in specs for d in s.shape
                      if d is None or d == -1)
    attempts = []
    for shared in ((False, True) if n_dyn_total > 1 else (False,)):
        ss, poly = _sym_specs(shared)
        if ss is not None:
            attempts.append((ss, poly))
        if not poly:
            break
    attempts.append(([jax.ShapeDtypeStruct(e.shape, e.dtype)
                      for e in examples], False))
    # vjp_order=1 bundles the backward program so jit.load's TranslatedLayer
    # is FINE-TUNABLE (reference TranslatedLayer is a trainable Layer). VJP
    # serialization can fail where the forward succeeds (symbolic-shape vjp
    # gaps), so a LATER shape mode with a working vjp beats an earlier one
    # without: keep the first inference-only success as fallback and keep
    # trying shape modes for a trainable artifact (review r4b).
    fallback = None   # (blob, poly, vjp_error)
    chosen = None
    for in_specs, poly in attempts:
        try:
            exported = jax_export.export(jax.jit(infer_fn_functional))(
                p_struct, b_struct, *in_specs)
        except Exception as e:   # noqa: BLE001 — try next shape mode
            # keep the cause: a silent exported=False cost a round-3
            # debugging session (to_static leaf-count corruption)
            meta['export_error'] = f'{e.__class__.__name__}: {e}'[:300]
            continue
        try:
            chosen = (exported.serialize(vjp_order=1), poly, None)
            break
        except Exception as e:   # noqa: BLE001 — inference-only candidate
            if fallback is None:
                try:
                    fallback = (exported.serialize(), poly,
                                f'{e.__class__.__name__}: {e}'[:300])
                except Exception as e2:   # noqa: BLE001
                    meta['export_error'] = \
                        f'{e2.__class__.__name__}: {e2}'[:300]
    if chosen is None and fallback is not None:
        chosen = fallback
    if chosen is not None:
        blob, poly, vjp_err = chosen
        with open(path + '.pdexec', 'wb') as f:
            f.write(blob)
        meta['exported'] = True
        meta['poly_batch'] = poly
        meta['vjp_exported'] = vjp_err is None
        if vjp_err is not None:
            # tells the user WHY their finetune loop will refuse
            meta['vjp_export_error'] = vjp_err
        meta.pop('export_error', None)
    if not meta['exported'] and os.path.exists(path + '.pdexec'):
        os.unlink(path + '.pdexec')   # drop stale program from a prior save


def _save_function(fn, path, input_spec):
    """jit.save for a function: .pdparams carries empty state; the .pdexec
    program takes only the inputs."""
    import json
    from ..framework_io import save as fsave
    raw = fn._fn if isinstance(fn, StaticFunction) else fn
    spec = input_spec or getattr(fn, '_input_spec', None)
    if spec is None:
        raise ValueError('jit.save of a function requires input_spec')
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in spec]
    fsave({'params': {}, 'buffers': {}}, path + '.pdparams')
    meta = {'class': getattr(raw, '__name__', 'function'), 'function': True,
            'input_spec': [{'shape': [(-1 if d is None else int(d))
                                      for d in s.shape],
                            'dtype': str(np.dtype(s.dtype).name)}
                           for s in specs]}

    def infer_fn_functional(params, buffers, *xs):
        from ..core.tensor import no_grad_ctx
        targs = [Tensor(x) for x in xs]
        with no_grad_ctx():
            res = raw(*targs)
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, res,
            is_leaf=lambda t: isinstance(t, Tensor))

    def infer_fn(*xs):
        return infer_fn_functional({}, {}, *xs)

    examples = [_spec_to_example(s) for s in specs]
    _export_artifacts(infer_fn, infer_fn_functional, {}, {}, specs, examples,
                      path, meta)
    with open(path + '.pdmodel', 'w') as f:
        json.dump(meta, f)


def load_saved_artifacts(path):
    """Load a jit.save'd prefix: (params, buffers, meta, exec_or_None).

    The serialized program is only deserialized when meta says the export
    succeeded — a stale .pdexec from an earlier save of a different model is
    ignored. Shared by jit.load and inference.Predictor.
    """
    import json
    from ..framework_io import load as fload
    state = fload(path + '.pdparams')

    def _arr(v):
        return jnp.asarray(getattr(v, '_value', v))
    params = {k: _arr(v) for k, v in state['params'].items()}
    buffers = {k: _arr(v) for k, v in state['buffers'].items()}
    with open(path + '.pdmodel') as f:
        meta = json.load(f)
    executable = None
    if meta.get('exported') and os.path.exists(path + '.pdexec'):
        from jax import export as jax_export
        with open(path + '.pdexec', 'rb') as f:
            executable = jax_export.deserialize(f.read())
    return params, buffers, meta, executable


def _flat_name(n):
    """Injective flattening of dotted program names into single-level
    attribute names ('_' escaped first, so 'a__weight' and 'a.weight'
    cannot collide — review r4b)."""
    return n.replace('_', '_u').replace('.', '_d')


class TranslatedLayer(Layer):
    """A jit.save'd program reloaded WITHOUT its Python class.

    Reference: fluid/dygraph/io.py TranslatedLayer (rebuilds a Layer from the
    __model__ ProgramDesc). Here the program is a serialized jax.export
    artifact (.pdexec): deserialization gives a callable XLA program; params
    and buffers come from the .pdparams archive and are passed as the leading
    pytree arguments.

    Like the reference, the result is a real Layer: its parameters are
    trainable when the artifact was serialized with its backward program
    (meta['vjp_exported'], the jit.save default) — the deploy-then-finetune
    workflow. Caveat: the program is traced in eval mode at save time, so
    dropout stays off and norm running stats stay frozen while fine-tuning
    (feature-extractor semantics).
    """

    def __init__(self, path):
        super().__init__()
        params, buffers, self._meta, self._exec = load_saved_artifacts(path)
        if self._exec is None:
            raise RuntimeError(
                f'{path}.pdexec missing or export failed at save time; '
                f'reconstruct the Layer and set_state_dict(jit.load raw dict)')
        from ..nn.layer_base import Parameter
        # registered under sanitized names ('.' nests in state_dict keys);
        # _tl_pnames keeps the original program-side names in order
        self._tl_pnames = list(params)
        self._tl_bnames = list(buffers)
        trainable = bool(self._meta.get('vjp_exported'))
        for n, v in params.items():
            p = Parameter(v)
            if not trainable:
                # no serialized backward program: advertising trainable
                # params would let a finetune loop run with grads silently
                # frozen (review r4b)
                p.stop_gradient = True
            self.add_parameter(_flat_name(n), p)
        for n, v in buffers.items():
            self.register_buffer(_flat_name(n), Tensor(v))
        self.eval()

    def train(self):
        if not self._meta.get('vjp_exported'):
            raise RuntimeError(
                'this artifact was serialized without its backward program '
                '(vjp_exported=false) — TranslatedLayer is inference-only; '
                're-save with the current jit.save to fine-tune')
        return super().train()

    def forward(self, *inputs):
        from ..core.dispatch import apply_op
        xs = [a if isinstance(a, Tensor) else Tensor(jnp.asarray(np.asarray(a)))
              for a in inputs]
        pts = [self._parameters[_flat_name(n)] for n in self._tl_pnames]
        bvals = {n: self._buffers[_flat_name(n)]._value
                 for n in self._tl_bnames}
        pnames, np_ = self._tl_pnames, len(self._tl_pnames)

        treedef_box = []

        def pure(*leaves):
            pvals = dict(zip(pnames, leaves[:np_]))
            out = self._exec.call(pvals, bvals, *leaves[np_:])
            # arbitrary output pytrees (dict returns etc.) ride through the
            # dispatch layer as flat leaves and are rebuilt below
            flat, td = jax.tree_util.tree_flatten(out)
            treedef_box.append(td)
            return tuple(flat) if len(flat) != 1 else flat[0]

        if self._meta.get('vjp_exported'):
            # through the dispatch layer: taped, so loss.backward() reaches
            # the registered Parameters via the serialized VJP program
            res = apply_op(pure, *pts, *xs)
        else:
            out = pure(*[t._value for t in pts], *[t._value for t in xs])
            res = jax.tree_util.tree_map(Tensor, out,
                                         is_leaf=lambda x: not isinstance(
                                             x, (list, tuple)))
        flat = list(res) if isinstance(res, (list, tuple)) else [res]
        return jax.tree_util.tree_unflatten(treedef_box[-1], flat)

    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=''):
        # original program-side (dotted) names, as the reference
        # TranslatedLayer; honors the Layer API's destination/prefix
        d = destination if destination is not None else {}
        for n in self._tl_pnames:
            d[structured_name_prefix + n] = self._parameters[_flat_name(n)]
        for n in self._tl_bnames:
            d[structured_name_prefix + n] = self._buffers[_flat_name(n)]
        return d


def load(path, **configs):
    """Reload a jit.save'd model. Returns a callable TranslatedLayer when the
    standalone program (.pdexec) exists; otherwise the raw state dict
    {params, buffers} for manual ``set_state_dict``."""
    if os.path.exists(path + '.pdexec') and os.path.exists(path + '.pdmodel'):
        try:
            # load_saved_artifacts makes the exported/stale decision itself
            return TranslatedLayer(path)
        except Exception as e:   # noqa: BLE001 — any deserialization failure
            # (RuntimeError, OSError, ValueError, jax.export version skew...)
            # degrades to the raw state dict rather than aborting the load
            import warnings
            warnings.warn(f'jit.load: standalone program at {path}.pdexec '
                          f'unusable ({e.__class__.__name__}: {e}); '
                          f'returning raw state dict')
    from ..framework_io import load as fload
    return fload(path + '.pdparams')


# ---- parity shims (reference: python/paddle/jit/__init__.py) -------------
declarative = to_static          # old alias


class ProgramTranslator:
    """Reference: jit/dy2static/program_translator.py. Tracing-based backend
    has no AST translator state; enable flag toggles to_static pass-through."""
    _instance = None
    enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static):
        ProgramTranslator.enabled = bool(enable_to_static)


def enable_to_static(flag):
    ProgramTranslator.get_instance().enable(flag)


def set_code_level(level=100):
    pass


def set_verbosity(level=0, also_to_stdout=False):
    pass


class dy2static:
    ProgramTranslator = ProgramTranslator
