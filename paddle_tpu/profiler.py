"""Profiler. Reference: python/paddle/fluid/profiler.py + new paddle.profiler.

TPU-native: wraps jax.profiler — traces go to TensorBoard-compatible
protobufs; RecordEvent maps to jax.profiler.TraceAnnotation.
"""
import contextlib
import time

import jax


class ProfilerTarget:
    CPU = 'cpu'
    GPU = 'gpu'
    TPU = 'tpu'


class RecordEvent:
    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir='./profiler_log'):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._started = False
        self._step_times = []
        self._last = None

    def start(self):
        if not self.timer_only:
            try:
                jax.profiler.start_trace(self.log_dir)
            except Exception:
                self.timer_only = True
        self._started = True
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now

    def stop(self):
        if self._started and not self.timer_only:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        self._started = False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit='ms'):
        if not self._step_times:
            return 'no steps recorded'
        import numpy as np
        ts = np.asarray(self._step_times) * 1000
        return (f'steps={len(ts)} mean={ts.mean():.2f}ms p50='
                f'{np.percentile(ts, 50):.2f}ms p99={np.percentile(ts, 99):.2f}ms')

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile'):
    p = Profiler(timer_only=False, log_dir=profile_path)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def start_profiler(state='All', tracer_option='Default'):
    jax.profiler.start_trace('./profiler_log')


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass


class ProfilerOptions:
    """Reference: python/paddle/utils/profiler.py ProfilerOptions — a dict
    of knobs; only the subset meaningful for jax.profiler is honored."""

    DEFAULT = {'state': 'All', 'sorted_key': 'default',
               'tracer_level': 'Default', 'batch_range': [0, 10],
               'output_thread_detail': False, 'profile_path': 'none',
               'timeline_path': 'none', 'op_summary_path': 'none'}

    def __init__(self, options=None):
        self._options = dict(self.DEFAULT)
        if options:
            self._options.update(options)

    def with_state(self, state):
        new = ProfilerOptions(self._options)
        new._options['state'] = state
        return new

    def __getitem__(self, name):
        return self._options[name]


def percentile(samples, q):
    """Nearest-rank percentile of an (unsorted) sample sequence; q in
    [0, 100]. Shared by StepTimer and the serving metrics so every latency
    number in the framework is computed the same way."""
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(len(s) - 1, int(len(s) * q / 100.0))]


class StepTimer:
    """Per-step host-side timing breakdown for the async train executor.

    Phases: ``data`` (host fetch/collate + H2D wait), ``dispatch`` (python
    overhead to enqueue the compiled step — what the async executor
    minimizes), ``readback`` (blocking D2H loss resolution at logging
    points). Attach with ``model._step_timer = StepTimer()`` before fit();
    read ``summary()`` after."""

    PHASES = ('data', 'dispatch', 'readback')

    def __init__(self):
        self.reset()

    def reset(self):
        self._samples = {p: [] for p in self.PHASES}
        self._pending = {p: 0.0 for p in self.PHASES}
        self.steps = 0
        self._t_start = time.perf_counter()

    def add(self, phase, seconds):
        self._pending[phase] = self._pending.get(phase, 0.0) + seconds

    @contextlib.contextmanager
    def span(self, phase):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(phase, time.perf_counter() - t0)

    def timed_iter(self, phase, iterable):
        """Wrap an iterator so the time blocked in next() books to phase."""
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            finally:
                self.add(phase, time.perf_counter() - t0)
            yield item

    def step_done(self):
        for p, v in self._pending.items():
            self._samples.setdefault(p, []).append(v)
        self._pending = {p: 0.0 for p in self._samples}
        self.steps += 1

    def summary(self):
        wall = time.perf_counter() - self._t_start
        out = {'steps': self.steps,
               'wall_s': wall,
               'steps_per_sec': self.steps / wall if wall > 0 else 0.0}
        for p, xs in self._samples.items():
            if not xs:
                continue
            out[p + '_ms_mean'] = 1e3 * sum(xs) / len(xs)
            out[p + '_ms_p50'] = 1e3 * percentile(xs, 50)
            out[p + '_ms_p99'] = 1e3 * percentile(xs, 99)
        return out


_profiler_singleton = None


def get_profiler(options=None):
    """Process-wide Profiler singleton (reference utils/profiler.py)."""
    global _profiler_singleton
    if _profiler_singleton is None:
        opts = options if isinstance(options, ProfilerOptions) \
            else ProfilerOptions(options)
        _profiler_singleton = Profiler(
            log_dir=opts['profile_path'] if opts['profile_path'] != 'none'
            else './profiler_log')
    return _profiler_singleton
