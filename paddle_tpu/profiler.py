"""Profiler. Reference: python/paddle/fluid/profiler.py + new paddle.profiler.

TPU-native: wraps jax.profiler — traces go to TensorBoard-compatible
protobufs; RecordEvent maps to jax.profiler.TraceAnnotation.
"""
import contextlib
import itertools
import time

import jax

from . import observability as _obs


class ProfilerTarget:
    CPU = 'cpu'
    GPU = 'gpu'
    TPU = 'tpu'


class RecordEvent:
    """User-facing profiling region. Forwards to an observability span
    (which itself wraps ``jax.profiler.TraceAnnotation`` when the platform
    provides it, and degrades to host-only timing otherwise).

    Misuse-hardened: ``begin()`` while already active is a no-op (no leaked
    second annotation), ``end()`` without a matching ``begin()`` is a
    no-op instead of an AttributeError."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._span = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        if self._span is not None:      # already active: do not re-enter
            return
        from .observability import trace as _trace
        span = _trace.Span(self.name)
        try:
            span.__enter__()
        except Exception:               # host-only fallback of last resort
            span = _trace.NULL_SPAN
        self._span = span

    def end(self):
        span = self._span
        if span is None:                # end() without begin(): no-op
            return
        self._span = None
        try:
            span.__exit__(None, None, None)
        except Exception:
            pass


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir='./profiler_log'):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._started = False
        self._step_times = []
        self._last = None

    def start(self):
        if not self.timer_only:
            try:
                jax.profiler.start_trace(self.log_dir)
            except Exception:
                self.timer_only = True
        self._started = True
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now

    def stop(self):
        if self._started and not self.timer_only:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        self._started = False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit='ms'):
        if not self._step_times:
            return 'no steps recorded'
        import numpy as np
        ts = np.asarray(self._step_times) * 1000
        return (f'steps={len(ts)} mean={ts.mean():.2f}ms p50='
                f'{np.percentile(ts, 50):.2f}ms p99={np.percentile(ts, 99):.2f}ms')

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile'):
    p = Profiler(timer_only=False, log_dir=profile_path)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def start_profiler(state='All', tracer_option='Default'):
    jax.profiler.start_trace('./profiler_log')


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass


class ProfilerOptions:
    """Reference: python/paddle/utils/profiler.py ProfilerOptions — a dict
    of knobs; only the subset meaningful for jax.profiler is honored."""

    DEFAULT = {'state': 'All', 'sorted_key': 'default',
               'tracer_level': 'Default', 'batch_range': [0, 10],
               'output_thread_detail': False, 'profile_path': 'none',
               'timeline_path': 'none', 'op_summary_path': 'none'}

    def __init__(self, options=None):
        self._options = dict(self.DEFAULT)
        if options:
            self._options.update(options)

    def with_state(self, state):
        new = ProfilerOptions(self._options)
        new._options['state'] = state
        return new

    def __getitem__(self, name):
        return self._options[name]


def percentile(samples, q):
    """Nearest-rank percentile — delegates to the one canonical
    implementation in :mod:`paddle_tpu.observability.registry`. Returns
    ``None`` for empty input, the lone element for a single sample, and
    clamps q into [0, 100]."""
    return _obs.percentile(samples, q)


class StepTimer:
    """Per-step host-side timing breakdown for the async train executor.

    Phases: ``data`` (host fetch/collate + H2D wait), ``dispatch`` (python
    overhead to enqueue the compiled step — what the async executor
    minimizes), ``readback`` (blocking D2H loss resolution at logging
    points). Attach with ``model._step_timer = StepTimer()`` before fit();
    read ``summary()`` after.

    Since the observability PR this is a *view* over registry histograms:
    each instance owns ``train.{phase}_ms{timer=tN}`` series (values in
    milliseconds) plus a ``train.timer_steps`` counter, so the same numbers
    ``summary()`` reports are visible in ``observability.snapshot()``.
    When observability is disabled the timer keeps working on private,
    unregistered metric objects."""

    PHASES = ('data', 'dispatch', 'readback')
    _seq = itertools.count()

    def __init__(self):
        self.labels = {'timer': f't{next(StepTimer._seq)}'}
        self._hists = {}
        self._steps = None
        self.reset()

    def _histogram(self, phase):
        h = self._hists.get(phase)
        if h is None:
            name = f'train.{phase}_ms'
            if _obs.enabled():
                h = _obs.registry().histogram(name, self.labels)
            else:
                h = _obs.Histogram(name, self.labels)
            self._hists[phase] = h
        return h

    def reset(self):
        self._hists.clear()
        if _obs.enabled():
            self._steps = _obs.registry().counter(
                'train.timer_steps', self.labels)
        else:
            self._steps = _obs.Counter('train.timer_steps', self.labels)
        self._steps.reset()
        for p in self.PHASES:
            self._histogram(p).reset()
        self._pending = {p: 0.0 for p in self.PHASES}
        self._t_start = time.perf_counter()

    @property
    def steps(self):
        return int(self._steps.value)

    def add(self, phase, seconds):
        self._pending[phase] = self._pending.get(phase, 0.0) + seconds

    @contextlib.contextmanager
    def span(self, phase):
        """Books elapsed time to ``phase`` ONLY when the body completes.
        A raising step would otherwise record the partial duration up to
        the raise — a misleadingly small sample polluting the p50/p99."""
        t0 = time.perf_counter()
        yield
        self.add(phase, time.perf_counter() - t0)

    def timed_iter(self, phase, iterable):
        """Wrap an iterator so the time blocked in next() books to phase.
        A raising ``next()`` (other than StopIteration) books nothing."""
        it = iter(iterable)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self.add(phase, time.perf_counter() - t0)
            yield item

    def abort_step(self):
        """Discard the partially-accumulated step (the step fn raised):
        pending phase durations are dropped instead of observed."""
        self._pending = {p: 0.0 for p in self._pending}

    def step_done(self):
        for p, v in self._pending.items():
            self._histogram(p).observe(1e3 * v)
        self._pending = {p: 0.0 for p in self._pending}
        self._steps.inc()

    def summary(self):
        wall = time.perf_counter() - self._t_start
        steps = self.steps
        out = {'steps': steps,
               'wall_s': wall,
               'steps_per_sec': steps / wall if wall > 0 else 0.0}
        for p, h in self._hists.items():
            c = h.count
            if not c:
                continue
            out[p + '_ms_mean'] = h.sum / c
            out[p + '_ms_p50'] = h.percentile(50)
            out[p + '_ms_p99'] = h.percentile(99)
        return out


_profiler_singleton = None


def get_profiler(options=None):
    """Process-wide Profiler singleton (reference utils/profiler.py)."""
    global _profiler_singleton
    if _profiler_singleton is None:
        opts = options if isinstance(options, ProfilerOptions) \
            else ProfilerOptions(options)
        _profiler_singleton = Profiler(
            log_dir=opts['profile_path'] if opts['profile_path'] != 'none'
            else './profiler_log')
    return _profiler_singleton
