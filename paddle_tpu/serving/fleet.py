"""Fleet front door: health-gated replica routing, failover, autoscaling.

One engine is one failure domain: its circuit breaker opening, its queue
filling, or its process dying takes every queued request with it. This
module composes N single-replica engines (``InferenceEngine`` or
``GenerationEngine``) into one servable unit with an availability story:

- :class:`ReplicaSet` owns the replicas. It deregisters each engine's
  individual ``/readyz`` probe (one dead replica must not 503 the whole
  process) and registers a single aggregate probe — ready iff at least
  one replica is ready. ``spawn()`` builds a new replica from the
  factory and **clones the template replica's compiled executables**
  (AOT prefill/decode for generation, bucket cache entries for batch
  inference), so scale-up serves its first request without a cold
  compile — provable via the new engine's trace counter.
- :class:`FleetRouter` is the front door. ``submit()`` routes to the
  least-loaded replica whose readiness probe passes and whose circuit
  breaker is closed. A replica failure mid-request fails over by
  resubmitting with the SAME :class:`~..observability.RequestRecord`,
  original enqueue timestamp, and original absolute deadline (the
  engines' ``_record``/``_enqueue_t``/``_deadline_t`` hooks), so no
  request is lost and SLO accounting stays truthful. Generation streams
  are deduplicated by token index against the engines' byte-identical
  seeded regeneration: a rerouted stream never emits a token twice.
  Load is shed (``QueueFullError`` with a ``retry_after_ms`` hint from
  the observed queue-wait p99) only when EVERY replica is saturated.
  ``drain()``/``decommission()`` stop routing to a replica, finish its
  in-flight work, and retire it — a rolling restart drops nothing.
- :class:`Autoscaler` evaluates per-replica SLO rules on
  ``serve.queue_wait_ms`` p99 (delta-window, debounced): sustained
  breach scales up from the warm template; a replica idle past
  ``idle_s`` is gracefully drained back down between ``min``/``max``.

Failure handling is event-driven through ONE control thread: engines
report attempt outcomes by finishing a per-attempt record facade, which
posts to the router's event queue (a leaf lock — nothing is called
under it); the control thread serializes failover, parked-request
retry, hedged retries, the health sweep, and autoscaler ticks. No
router lock is ever held across an engine call.

Chaos inject points: ``fleet.route`` (routing decision; an armed fault
parks the request for retry instead of losing it) and
``fleet.failover`` (health sweep; an armed fault SIGKILL-simulates a
replica via ``shutdown(drain=False)``, exercising the full failover
path — ``tools/fleet_drill.py`` builds on this).

Env knobs: ``PADDLE_TPU_FLEET_REPLICAS`` (initial size),
``PADDLE_TPU_FLEET_MIN`` / ``PADDLE_TPU_FLEET_MAX`` (autoscale bounds),
``PADDLE_TPU_FLEET_QWAIT_P99_MS`` (scale-up threshold),
``PADDLE_TPU_FLEET_IDLE_S`` (scale-down idle window),
``PADDLE_TPU_FLEET_COOLDOWN_S`` (between scale ops).
"""
import itertools
import os
import threading
import time
from collections import deque
from concurrent.futures import Future

from .. import fault
from .. import observability as _obs
from ..fault.errors import InjectedFault
from ..observability import slo as _slo
from .errors import DeadlineExceededError, EngineClosedError, QueueFullError
from .generation import GenerationEngine, GenerationFuture

ENV_REPLICAS = 'PADDLE_TPU_FLEET_REPLICAS'
ENV_MIN = 'PADDLE_TPU_FLEET_MIN'
ENV_MAX = 'PADDLE_TPU_FLEET_MAX'
ENV_QWAIT = 'PADDLE_TPU_FLEET_QWAIT_P99_MS'
ENV_IDLE = 'PADDLE_TPU_FLEET_IDLE_S'
ENV_COOLDOWN = 'PADDLE_TPU_FLEET_COOLDOWN_S'

_BREAKER_CODE = {'closed': 0, 'open': 1, 'half_open': 2}


def _env_num(name, default, cast):
    try:
        return cast(os.environ.get(name, default))
    except (TypeError, ValueError):
        return cast(default)


def _retryable(error):
    """Failover classification: deadline expiry and caller mistakes are
    terminal; infrastructure failures (closed engines, open breakers,
    injected faults, device errors) are worth another replica."""
    if error is None:
        return True
    if isinstance(error, (DeadlineExceededError, ValueError, TypeError,
                          AssertionError, KeyboardInterrupt)):
        return False
    return True


def _clone_warmth(src, dst):
    """Copy ``src``'s compiled executables into ``dst`` (same factory ⇒
    same model/config/geometry ⇒ same traced signatures). Generation
    engines share AOT prefill/decode executables; batch engines share
    bucket-cache entries. Both engine families pass params as traced
    ARGUMENTS (never closed-over constants), which is what makes the
    executables replica-portable. The clone marks ``dst`` warm: its
    first request runs with zero retraces — the scale-up-without-cold-
    compile proof the fleet drill asserts on."""
    aot_src = getattr(src, '_aot', None)
    if aot_src is not None and hasattr(dst, '_aot'):
        dst._aot.update(aot_src)
    cache_src = getattr(src, '_cache', None)
    cache_dst = getattr(dst, '_cache', None)
    if cache_src is not None and cache_dst is not None:
        with cache_src._lock:
            entries = dict(cache_src._fns)
        with cache_dst._lock:
            for key, fn in entries.items():
                cache_dst._fns.setdefault(key, fn)
            cache_dst.prebuilt += len(entries)
    dst._warmed = True


class _AttemptRecord:
    """Per-attempt facade over the master :class:`RequestRecord`.

    The master record's ``finish`` is first-outcome-wins; a failed
    attempt finishing it would permanently seal the request's trace
    before failover even starts. The facade forwards notes (annotated
    with the replica) to the master, keeps its own split-parts counter,
    and intercepts ``finish`` to post an attempt-outcome event to the
    router; only the router finishes the master, on terminal outcomes.
    """

    __slots__ = ('master', 'replica', 'rid', 'attempt', 'outcome', 'error',
                 '_parts_left', '_alock', '_on_done')

    def __init__(self, master, replica_name, on_done):
        self.master = master
        self.replica = replica_name
        self.rid = master.rid
        self.attempt = None          # backref set by the router
        self.outcome = None
        self.error = None
        self._parts_left = 1
        self._alock = threading.Lock()
        self._on_done = on_done

    def note(self, ev, **attrs):
        self.master.note(ev, replica=self.replica, **attrs)
        return self

    def note_decode(self, pos):
        self.master.note_decode(pos)
        return self

    def expect_parts(self, n):
        with self._alock:
            self._parts_left = max(1, int(n))
        return self

    def part_retired(self):
        with self._alock:
            self._parts_left -= 1
            return self._parts_left <= 0

    def finish(self, outcome, error=None):
        with self._alock:
            if self.outcome is not None:
                return self
            self.outcome = str(outcome)
            self.error = error
        # outside _alock: posts to the router's leaf event queue (the
        # engine may be holding its scheduler lock right now)
        self._on_done(self)
        return self


class Replica:
    """One engine plus its fleet-visible state."""

    READY = 'ready'
    DRAINING = 'draining'
    DEAD = 'dead'
    STOPPED = 'stopped'

    __slots__ = ('name', 'engine', 'kind', 'state', 'idle_since')

    def __init__(self, name, engine, kind):
        self.name = name
        self.engine = engine
        self.kind = kind
        self.state = Replica.READY
        self.idle_since = None

    @property
    def label(self):
        """The engine's metrics label value (``e0``/``g3``) — the key the
        autoscaler's per-replica queue-wait rules select on."""
        if self.kind == 'gen':
            return self.engine.labels['engine']
        return self.engine._stats.labels['engine']

    def probe(self):
        return self.engine._readiness_probe()


class ReplicaSet:
    """Owns the replicas: lifecycle, readiness aggregation, warm spawn."""

    _seq = itertools.count()

    def __init__(self, factory=None, *, replicas=None, initial=None,
                 min_replicas=None, max_replicas=None, name=None):
        self.name = name or f'fleet{next(ReplicaSet._seq)}'
        self._factory = factory
        self._lock = threading.Lock()
        self._replicas = {}          # name -> Replica (insertion ordered)
        self._ridx = itertools.count()
        self.kind = None
        self.min_replicas = int(
            min_replicas if min_replicas is not None
            else _env_num(ENV_MIN, 1, int))
        mx = (max_replicas if max_replicas is not None
              else _env_num(ENV_MAX, 0, int))
        self.max_replicas = int(mx) if mx else None
        for eng in (replicas or ()):
            self.add(eng)
        if factory is not None and not self._replicas:
            n = int(initial if initial is not None
                    else _env_num(ENV_REPLICAS, max(1, self.min_replicas),
                                  int))
            for _ in range(max(1, n)):
                self.add(factory())
        self._probe_name = f'fleet.{self.name}'
        _obs.add_readiness(self._probe_name, self._aggregate_probe)

    # ---- membership ------------------------------------------------------
    def add(self, engine):
        kind = 'gen' if isinstance(engine, GenerationEngine) else 'infer'
        if self.kind is None:
            self.kind = kind
        elif kind != self.kind:
            raise ValueError(
                f'mixed fleet: set is {self.kind!r}, engine is {kind!r}')
        rep = Replica(f'{self.name}/r{next(self._ridx)}', engine, kind)
        # the readiness plane ANDs every registered probe; a replica must
        # contribute through the fleet aggregate, not gate the process
        _obs.remove_readiness(engine._probe_name)
        with self._lock:
            self._replicas[rep.name] = rep
        self._publish_size()
        _obs.record_event('fleet.replica_added', fleet=self.name,
                          replica=rep.name)
        return rep

    def spawn(self):
        """Build a replica from the factory and clone a ready template's
        compiled executables so it serves without a cold compile."""
        if self._factory is None:
            raise RuntimeError('ReplicaSet has no factory; cannot spawn')
        t0 = time.perf_counter()
        engine = self._factory()
        template = next((r for r in self.snapshot()
                         if r.state == Replica.READY), None)
        if template is not None:
            _clone_warmth(template.engine, engine)
        rep = self.add(engine)
        dt_ms = 1e3 * (time.perf_counter() - t0)
        _obs.histogram('fleet.scale_up_ms', {'fleet': self.name}) \
            .observe(dt_ms)
        _obs.counter('fleet.scale_up', {'fleet': self.name}).inc()
        _obs.record_event('fleet.scale_up', fleet=self.name,
                          replica=rep.name, ms=round(dt_ms, 3))
        return rep

    def snapshot(self):
        with self._lock:
            return list(self._replicas.values())

    def get(self, name):
        with self._lock:
            return self._replicas.get(name)

    def counts(self):
        with self._lock:
            reps = list(self._replicas.values())
        alive = sum(1 for r in reps
                    if r.state in (Replica.READY, Replica.DRAINING))
        ready = sum(1 for r in reps if r.state == Replica.READY)
        return alive, ready

    # ---- lifecycle -------------------------------------------------------
    def drain(self, name, timeout=None):
        """Graceful: stop admitting (router filters on READY), finish all
        queued + in-flight work, then retire. Zero dropped requests."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or rep.state in (Replica.DEAD, Replica.STOPPED):
                return rep
            rep.state = Replica.DRAINING
        self._publish_size()
        rep.engine.shutdown(drain=True, timeout=timeout)
        with self._lock:
            rep.state = Replica.STOPPED
        self._publish_size()
        _obs.record_event('fleet.replica_drained', fleet=self.name,
                          replica=name)
        return rep

    def kill(self, name):
        """Abrupt: fail everything queued/in-flight on the replica
        (EngineClosedError) — the SIGKILL simulation the failover path
        and the chaos drill are tested against."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or rep.state in (Replica.DEAD, Replica.STOPPED):
                return rep
            rep.state = Replica.DEAD
        self._publish_size()
        rep.engine.shutdown(drain=False)
        _obs.record_event('fleet.replica_killed', fleet=self.name,
                          replica=name)
        return rep

    def mark_dead(self, name):
        with self._lock:
            rep = self._replicas.get(name)
            if rep is not None and rep.state == Replica.READY:
                rep.state = Replica.DEAD
        self._publish_size()
        return rep

    def decommission(self, name, timeout=None):
        rep = self.drain(name, timeout=timeout)
        with self._lock:
            self._replicas.pop(name, None)
        self._publish_size()
        _obs.record_event('fleet.replica_decommissioned', fleet=self.name,
                          replica=name)
        return rep

    def close(self, drain=True, timeout=None):
        for rep in self.snapshot():
            if rep.state in (Replica.READY, Replica.DRAINING):
                if drain:
                    self.drain(rep.name, timeout=timeout)
                else:
                    self.kill(rep.name)
        _obs.remove_readiness(self._probe_name)

    # ---- readiness -------------------------------------------------------
    def _aggregate_probe(self):
        """The fleet's single /readyz contribution: ready iff >=1 replica
        is ready (per-replica detail included for operators)."""
        detail, any_ready = {}, False
        for rep in self.snapshot():
            if rep.state != Replica.READY:
                detail[rep.name] = {'ready': False, 'state': rep.state}
                continue
            try:
                p = rep.probe()
            except Exception as e:
                p = {'ready': False, 'error': type(e).__name__}
            detail[rep.name] = p
            any_ready = any_ready or bool(p.get('ready'))
        return {'ready': any_ready, 'replicas': detail}

    def _publish_size(self):
        alive, ready = self.counts()
        _obs.gauge('fleet.replicas', {'fleet': self.name}).set(alive)
        _obs.gauge('fleet.replicas_ready', {'fleet': self.name}).set(ready)


class _Attempt:
    """One (request, replica) try."""

    __slots__ = ('freq', 'replica', 'record', 'inner', 'started',
                 'subscribed')

    def __init__(self, freq, replica, started):
        self.freq = freq
        self.replica = replica
        self.record = None
        self.inner = None
        self.started = started
        self.subscribed = False


class _FleetRequest:
    """Router-side state for one front-door request across attempts."""

    __slots__ = ('fid', 'kind', 'payload', 'max_new', 'seed', 'future',
                 'master', 'enqueue_t', 'deadline_t', 'attempts',
                 'failovers', 'bounces', 'hedged', 'done', 'parked',
                 '_mlock', '_next_idx', '_buffer')

    def __init__(self, fid, kind, payload, max_new, seed, future, master,
                 enqueue_t, deadline_t):
        self.fid = fid
        self.kind = kind
        self.payload = payload
        self.max_new = max_new
        self.seed = seed
        self.future = future
        self.master = master
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        self.attempts = []
        self.failovers = 0
        self.bounces = 0
        self.hedged = False
        self.done = False
        self.parked = False
        # generation stream mirror: dedup-by-index against regenerated
        # tokens after failover (engines regenerate byte-identically from
        # seeded per-position keys; indices < _next_idx are re-plays)
        self._mlock = threading.Lock()
        self._next_idx = 0
        self._buffer = {}

    def mirror(self, ev, *args):
        """Inner-future listener: forward each token exactly once, in
        order, to the fleet-facing future. Completion is driven by the
        attempt record (router event), not by inner-future finish."""
        if ev != 'token':
            return
        idx, tok = args
        with self._mlock:
            if idx < self._next_idx or idx in self._buffer:
                return
            self._buffer[idx] = tok
            while self._next_idx in self._buffer:
                t = self._buffer.pop(self._next_idx)
                self._next_idx += 1
                self.future._append(t)


class Autoscaler:
    """SLO-driven sizing between ``min``/``max``: scales up when any
    replica's ``serve.queue_wait_ms`` p99 breaches the threshold for
    ``debounce`` consecutive evaluations, drains an idle replica down
    after ``idle_s``. Driven by the router's control thread via
    ``tick()`` — no thread of its own (spawn/drain run on short-lived
    workers so routing never blocks on a compile or a drain). Inert
    when observability is disabled (no queue-wait series to watch)."""

    def __init__(self, *, qwait_p99_ms=None, idle_s=None, cooldown_s=None,
                 debounce=2):
        self.qwait_p99_ms = float(
            qwait_p99_ms if qwait_p99_ms is not None
            else _env_num(ENV_QWAIT, 250.0, float))
        self.idle_s = float(idle_s if idle_s is not None
                            else _env_num(ENV_IDLE, 5.0, float))
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else _env_num(ENV_COOLDOWN, 2.0, float))
        self.debounce = max(1, int(debounce))
        self._watch = _slo.watcher()
        self._router = None
        self._last_scale_t = None
        self._busy = False           # one scale op in flight at a time

    def bind(self, router):
        self._router = router
        for rep in router.set.snapshot():
            self.track(rep)
        return self

    def track(self, rep):
        try:
            self._watch.rule(
                f'fleet.qwait.{rep.label}', 'serve.queue_wait_ms',
                self.qwait_p99_ms, labels={'engine': rep.label},
                stat='p99', cmp='>', debounce=self.debounce)
        except ValueError:
            pass                     # label re-added after decommission

    def untrack(self, rep):
        self._watch.remove_rule(f'fleet.qwait.{rep.label}')

    def firing(self):
        return [r.name for r in self._watch.rules if r.state == 'firing']

    def tick(self, now):
        """One evaluation + at most one scale decision. Called from the
        router control thread; scale work runs on a worker thread that
        reports back through the router's event queue."""
        router = self._router
        if router is None:
            return
        self._watch.evaluate()
        if self._busy:
            return
        if (self._last_scale_t is not None
                and now - self._last_scale_t < self.cooldown_s):
            return
        rset = router.set
        alive, _ = rset.counts()
        reps = [r for r in rset.snapshot() if r.state == Replica.READY]
        # delta-window SLO rules hold their last state when traffic stops
        # (no new samples = no transition); a fully idle fleet overrides a
        # stale 'firing' — there is no queue wait to scale for
        all_idle = bool(reps) and all(r.idle_since is not None
                                      for r in reps)
        if self.firing() and not all_idle:
            if rset.max_replicas is not None and alive >= rset.max_replicas:
                return
            if rset._factory is None:
                return
            self._busy = True
            self._last_scale_t = now
            threading.Thread(target=self._spawn_worker,
                             name='paddle-tpu-fleet-spawn',
                             daemon=True).start()
            return
        # scale down: an idle replica past the window, above the floor
        if alive <= rset.min_replicas:
            return
        victim = next((r for r in rset.snapshot()
                       if r.state == Replica.READY
                       and r.idle_since is not None
                       and now - r.idle_since >= self.idle_s), None)
        if victim is None:
            return
        self._busy = True
        self._last_scale_t = now
        threading.Thread(target=self._drain_worker, args=(victim,),
                         name='paddle-tpu-fleet-drain', daemon=True).start()

    def _spawn_worker(self):
        router = self._router
        try:
            rep = router.set.spawn()
            router._post(('scaled', rep, None))
        except Exception as e:
            router._post(('scaled', None, e))

    def _drain_worker(self, rep):
        router = self._router
        try:
            self.untrack(rep)
            router.set.decommission(rep.name)
            _obs.counter('fleet.scale_down', {'fleet': router.name}).inc()
            router._post(('scaled', None, None))
        except Exception as e:
            router._post(('scaled', None, e))


class FleetRouter:
    """The fleet's front door — see the module docstring for semantics.

    Lock hierarchy (one direction only, enforced by tools/lint.py's
    lock-cycle pass): router ``_lock`` (request tables) is never held
    across an engine call; engines finish attempt records under their
    scheduler locks, which only touches the router's ``_evcv`` event
    queue — a leaf lock under which nothing is called."""

    def __init__(self, replica_set, *, max_failovers=3, hedge_ms=None,
                 autoscaler=None, tick_s=0.02, clock=None):
        self.set = replica_set
        self.name = replica_set.name
        self.max_failovers = max(0, int(max_failovers))
        self.hedge_ms = hedge_ms
        self.autoscaler = autoscaler
        self.tick_s = float(tick_s)
        self._clock = clock or time.monotonic
        self._labels = {'fleet': self.name}
        self._lock = threading.Lock()
        self._inflight = {}          # fid -> _FleetRequest
        self._parked = deque()
        self._fseq = itertools.count(1)
        self._closed = False
        self._stopping = False
        self._evcv = threading.Condition()   # leaf: event queue only
        self._events = deque()
        if autoscaler is not None:
            autoscaler.bind(self)
        self._thread = threading.Thread(
            target=self._control_loop, name='paddle-tpu-fleet-router',
            daemon=True)
        self._thread.start()

    # ---- event plumbing --------------------------------------------------
    def _post(self, event):
        with self._evcv:
            self._events.append(event)
            self._evcv.notify_all()

    def _post_done(self, record):
        self._post(('done', record.attempt))

    # ---- front door ------------------------------------------------------
    def submit(self, *args, deadline_ms=None, max_new_tokens=32, seed=0,
               target=None, tenant='default', lane='interactive'):
        """Route one request. Generation fleets take ``submit(prompt,
        max_new_tokens=, seed=, deadline_ms=)`` and return a
        :class:`GenerationFuture`; inference fleets take
        ``submit(*inputs, deadline_ms=)`` and return a Future.

        ``target='model@host'`` bypasses replica scoring entirely and
        forwards to that :class:`~.host.ModelHost`'s hosted model (with
        ``tenant``/``lane`` riding along) — the multi-model hosting
        front door behind the same fleet API.

        Raises :class:`QueueFullError` (with ``retry_after_ms``) only
        when every replica is saturated."""
        if target is not None:
            from .host import resolve_target
            host, model = resolve_target(target)
            _obs.counter('fleet.host_routed', self._labels).inc()
            return host.submit(model, *args, tenant=tenant, lane=lane,
                               deadline_ms=deadline_ms,
                               max_new_tokens=max_new_tokens, seed=seed)
        kind = self.set.kind
        if kind is None or self._closed:
            raise EngineClosedError('fleet router is closed or empty')
        now = self._clock()
        deadline_t = (now + deadline_ms / 1e3
                      if deadline_ms is not None else None)
        master = _obs.start_request('fleet', engine=self.name,
                                    fleet_kind=kind)
        if kind == 'gen':
            if len(args) != 1:
                raise TypeError('generation fleet submit() takes exactly '
                                'one prompt argument')
            payload = args[0]
            fut = GenerationFuture()
        else:
            payload = args
            fut = Future()
        fut.request_id = master.rid
        freq = _FleetRequest(next(self._fseq), kind, payload,
                             int(max_new_tokens), seed, fut, master, now,
                             deadline_t)
        with self._lock:
            self._inflight[freq.fid] = freq
        _obs.counter('fleet.submitted', self._labels).inc()
        master.note('enqueue', fleet=self.name)
        try:
            verdict = self._dispatch(freq)
        except Exception as e:
            self._fail(freq, 'error', e)
            raise
        if verdict == 'shed':
            err = self._shed(freq)
            raise err
        if verdict == 'park':
            self._park(freq)
        return fut

    # ---- routing ---------------------------------------------------------
    def _dispatch(self, freq, exclude=()):
        """Try to place ``freq`` on the best replica. Returns ``'ok'``
        (attempt in flight — rejections come back as events), ``'park'``
        (nothing routable right now, retry on the control loop), or
        ``'shed'`` (every replica saturated)."""
        try:
            fault.inject('fleet.route')
        except InjectedFault:
            _obs.counter('fleet.route_faults', self._labels).inc()
            freq.master.note('route_fault')
            return 'park'
        ready = [r for r in self.set.snapshot()
                 if r.state == Replica.READY]
        scored, saturated = [], 0
        for rep in ready:
            try:
                p = rep.probe()
            except Exception:
                continue
            healthy = (p.get('breaker') == 'closed'
                       and not p.get('closed'))
            full = (p.get('queue_depth', 0)
                    >= p.get('queue_capacity', 1))
            if healthy and full:
                saturated += 1
            # warmth is a preference, not a gate: a cold replica (fresh
            # spawn before its first request) still admits — routing away
            # from it forever would deadlock an entirely-cold fleet
            if healthy and not full and rep.name not in exclude:
                scored.append((not p.get('warm'), p.get('queue_depth', 0),
                               rep.name, rep))
        if not scored:
            # every replica is healthy-but-full -> backpressure; anything
            # else (breakers open, draining, spawning) may clear -> park
            if ready and saturated == len(ready):
                return 'shed'
            return 'park'
        scored.sort(key=lambda t: t[:2])
        cold, depth, _, rep = scored[0]
        att = _Attempt(freq, rep, self._clock())
        rec = _AttemptRecord(freq.master, rep.name, self._post_done)
        rec.attempt = att
        att.record = rec
        with self._lock:
            if freq.done:
                return 'ok'
            freq.attempts.append(att)
        try:
            if freq.kind == 'gen':
                inner = rep.engine.submit(
                    freq.payload, max_new_tokens=freq.max_new,
                    seed=freq.seed, _record=rec,
                    _enqueue_t=freq.enqueue_t, _deadline_t=freq.deadline_t)
            else:
                inner = rep.engine.submit(
                    *freq.payload, _record=rec,
                    _enqueue_t=freq.enqueue_t, _deadline_t=freq.deadline_t)
        except (QueueFullError, EngineClosedError, DeadlineExceededError):
            # the engine finished the attempt record ('rejected', or
            # 'expired' from the submit-time deadline fast-fail); that
            # event — the single failure path — drives the reroute
            return 'ok'
        except Exception:
            with self._lock:
                if att in freq.attempts:
                    freq.attempts.remove(att)
            raise
        att.inner = inner
        if freq.kind == 'gen':
            inner._subscribe(freq.mirror)
            att.subscribed = True
        freq.master.note('route', replica=rep.name, depth=depth)
        return 'ok'

    # ---- outcomes --------------------------------------------------------
    def _complete(self, freq, result):
        with self._lock:
            if freq.done:
                return
            freq.done = True
            self._inflight.pop(freq.fid, None)
        freq.master.finish('ok')
        if freq.kind == 'gen':
            freq.future._finish(None)
        else:
            try:
                freq.future.set_result(result)
            except Exception:
                pass                 # hedged duplicate already resolved it
        _obs.counter('fleet.completed', self._labels).inc()

    def _fail(self, freq, outcome, error):
        with self._lock:
            if freq.done:
                return
            freq.done = True
            self._inflight.pop(freq.fid, None)
            freq.parked = False
        freq.master.finish(outcome, error)
        if freq.kind == 'gen':
            freq.future._finish(error)
        else:
            try:
                freq.future.set_exception(error)
            except Exception:
                pass
        _obs.counter('fleet.failed', {**self._labels,
                                      'outcome': outcome}).inc()

    def _shed(self, freq):
        """All replicas saturated: reject with a useful backoff hint."""
        cap = depth = 0
        for rep in self.set.snapshot():
            if rep.state != Replica.READY:
                continue
            try:
                p = rep.probe()
            except Exception:
                continue
            cap += int(p.get('queue_capacity', 0))
            depth += int(p.get('queue_depth', 0))
        err = QueueFullError(cap, depth,
                             retry_after_ms=self._retry_after_ms())
        _obs.counter('fleet.shed', self._labels).inc()
        freq.master.note('shed', retry_after_ms=err.retry_after_ms)
        self._fail(freq, 'rejected', err)
        return err

    def _retry_after_ms(self):
        """Backoff hint from the observed queue-wait distribution."""
        best = None
        if _obs.enabled():
            reg = _obs.registry()
            for rep in self.set.snapshot():
                m = reg.find('serve.queue_wait_ms', {'engine': rep.label})
                if m is not None:
                    v = m.percentile(99)
                    if v:
                        best = max(best or 0.0, v)
        return round(best, 3) if best else 50.0

    def _park(self, freq):
        with self._lock:
            if freq.done or freq.parked:
                return
            freq.parked = True
            self._parked.append(freq)
        freq.master.note('park')

    # ---- control thread --------------------------------------------------
    def _control_loop(self):
        while True:
            with self._evcv:
                if not self._events and not self._stopping:
                    self._evcv.wait(self.tick_s)
                events = list(self._events)
                self._events.clear()
                stopping = self._stopping
            for ev in events:
                try:
                    self._handle(ev)
                except Exception:
                    _obs.counter('fleet.control_errors',
                                 self._labels).inc()
            if stopping and not events:
                return
            try:
                now = self._clock()
                self._sweep(now)
                self._tick_parked(now)
                self._tick_hedges(now)
                if self.autoscaler is not None:
                    self.autoscaler.tick(now)
            except Exception:
                _obs.counter('fleet.control_errors', self._labels).inc()

    def _handle(self, ev):
        kind = ev[0]
        if kind == 'done':
            self._handle_done(ev[1])
        elif kind == 'scaled':
            _, rep, error = ev
            if self.autoscaler is not None:
                self.autoscaler._busy = False
                if rep is not None:
                    self.autoscaler.track(rep)
            if error is not None:
                _obs.counter('fleet.scale_errors', self._labels).inc()

    def _handle_done(self, att):
        freq, rec = att.freq, att.record
        outcome, error = rec.outcome, rec.error
        if outcome == 'ok':
            # the engine can finish a request between its submit()
            # returning and the router wiring the attempt up; re-post
            # until the dispatch path has finished registering it
            if (freq.kind == 'gen' and not att.subscribed) or \
                    (freq.kind == 'infer' and att.inner is None):
                self._post(('done', att))
                return
        with self._lock:
            if att not in freq.attempts:
                return               # stale/aborted attempt
            freq.attempts.remove(att)
            if freq.done:
                return
            racing = len(freq.attempts)   # hedge twin still in flight?
        if outcome == 'ok':
            if freq.kind == 'infer':
                try:
                    result = att.inner.result(timeout=10.0)
                except Exception as e:
                    self._failover(freq, att, 'error', e, racing)
                    return
                self._complete(freq, result)
            else:
                # every token was mirrored before the engine finished the
                # attempt record (emit precedes retire in the scheduler)
                self._complete(freq, None)
            return
        self._failover(freq, att, outcome, error, racing)

    def _failover(self, freq, att, outcome, error, racing):
        now = self._clock()
        admitted = outcome != 'rejected'
        if isinstance(error, QueueFullError):
            freq.bounces += 1
        if admitted:
            freq.failovers += 1
            _obs.counter('fleet.failover', self._labels).inc()
            freq.master.note(
                'failover', frm=att.replica.name,
                error=(type(error).__name__ if error is not None
                       else outcome))
            _obs.record_event('fleet.failover', fleet=self.name,
                              replica=att.replica.name, outcome=outcome)
        if racing:
            return                   # a hedged twin is still running
        deadline_passed = (freq.deadline_t is not None
                           and now > freq.deadline_t)
        if deadline_passed and _retryable(error):
            waited = (now - freq.enqueue_t) * 1e3
            limit = (freq.deadline_t - freq.enqueue_t) * 1e3
            error = DeadlineExceededError(waited, limit)
            self._fail(freq, 'expired', error)
            return
        if not _retryable(error):
            self._fail(freq, outcome if outcome != 'ok' else 'error',
                       error)
            return
        if freq.failovers > self.max_failovers:
            self._fail(freq, 'error', error if error is not None
                       else RuntimeError('fleet failovers exhausted'))
            return
        if freq.bounces > max(8, 4 * len(self.set.snapshot())):
            self._shed(freq)
            return
        try:
            verdict = self._dispatch(freq, exclude=(att.replica.name,))
        except Exception as e:
            self._fail(freq, 'error', e)
            return
        if verdict == 'park':
            self._park(freq)
        elif verdict == 'shed':
            self._shed(freq)

    def _tick_parked(self, now):
        with self._lock:
            items = [f for f in self._parked]
        for freq in items:
            if freq.done:
                with self._lock:
                    if freq in self._parked:
                        self._parked.remove(freq)
                continue
            if freq.deadline_t is not None and now > freq.deadline_t:
                waited = (now - freq.enqueue_t) * 1e3
                limit = (freq.deadline_t - freq.enqueue_t) * 1e3
                self._fail(freq, 'expired',
                           DeadlineExceededError(waited, limit))
                continue
            with self._lock:
                if freq in self._parked:
                    self._parked.remove(freq)
                freq.parked = False
            try:
                verdict = self._dispatch(freq)
            except Exception as e:
                self._fail(freq, 'error', e)
                continue
            if verdict == 'park':
                self._park(freq)
            elif verdict == 'shed':
                self._shed(freq)

    def _tick_hedges(self, now):
        """Deadline-risk mitigation for batch inference: a request stuck
        on one replica past ``hedge_ms`` gets a second, racing attempt on
        another; first finish wins. Streams are never hedged — two
        concurrent emitters cannot both be byte-exact."""
        if self.hedge_ms is None or self.set.kind != 'infer':
            return
        with self._lock:
            candidates = [
                f for f in self._inflight.values()
                if (not f.done and not f.parked and not f.hedged
                    and len(f.attempts) == 1
                    and now - f.attempts[0].started > self.hedge_ms / 1e3)]
            for f in candidates:
                f.hedged = True
        for freq in candidates:
            primary = freq.attempts[0].replica.name if freq.attempts else ''
            _obs.counter('fleet.hedge', self._labels).inc()
            freq.master.note('hedge', primary=primary)
            try:
                self._dispatch(freq, exclude=(primary,))
            except Exception:
                pass                 # primary attempt is still running

    def _sweep(self, now):
        """Health pass: chaos hook, per-replica gauges, dead-replica
        detection (synthesizing failures for attempts stranded on an
        engine that died without failing its futures), idle tracking."""
        for rep in self.set.snapshot():
            if rep.state != Replica.READY:
                continue
            try:
                fault.inject('fleet.failover')
            except InjectedFault:
                _obs.counter('fleet.replicas_killed', self._labels).inc()
                self.set.kill(rep.name)
                self._strand_attempts(rep)
                continue
            labels = {'fleet': self.name, 'replica': rep.name}
            try:
                p = rep.probe()
            except Exception:
                p = None
            closed = bool(getattr(rep.engine, '_closed', False))
            if p is None or closed:
                self.set.mark_dead(rep.name)
                _obs.gauge('fleet.replica_breaker', labels) \
                    .set(_BREAKER_CODE['open'])
                self._strand_attempts(rep)
                continue
            depth = int(p.get('queue_depth', 0))
            _obs.gauge('fleet.replica_depth', labels).set(depth)
            _obs.gauge('fleet.replica_breaker', labels).set(
                _BREAKER_CODE.get(p.get('breaker'), 1))
            with self._lock:
                busy = any(a.replica is rep
                           for f in self._inflight.values()
                           for a in f.attempts)
            if depth == 0 and not busy:
                if rep.idle_since is None:
                    rep.idle_since = now
            else:
                rep.idle_since = None

    def _strand_attempts(self, rep):
        """Fail over every attempt still pointing at a dead replica. The
        finish facade is idempotent, so attempts the engine already
        failed on shutdown are unaffected."""
        with self._lock:
            atts = [a for f in self._inflight.values()
                    for a in f.attempts if a.replica is rep]
        for a in atts:
            a.record.finish('cancelled',
                            EngineClosedError('replica dead'))

    # ---- operator API ----------------------------------------------------
    def drain(self, name, timeout=None):
        """Stop routing to ``name``, finish its in-flight work."""
        return self.set.drain(name, timeout=timeout)

    def decommission(self, name, timeout=None):
        rep = self.set.get(name)
        if rep is not None and self.autoscaler is not None:
            self.autoscaler.untrack(rep)
        return self.set.decommission(name, timeout=timeout)

    def stats(self):
        alive, ready = self.set.counts()
        with self._lock:
            inflight = len(self._inflight)
            parked = len(self._parked)
        from ..parallel import mesh_engine as _mesh
        return {'fleet': self.name, 'kind': self.set.kind,
                'replicas': alive, 'replicas_ready': ready,
                'inflight': inflight, 'parked': parked,
                'replica_states': {r.name: r.state
                                   for r in self.set.snapshot()},
                'replica_mesh': {r.name: max(1, _mesh.mesh_size(r.engine))
                                 for r in self.set.snapshot()}}

    def close(self, drain=True, timeout=None):
        with self._lock:
            self._closed = True
        self.set.close(drain=drain, timeout=timeout)
        with self._evcv:
            self._stopping = True
            self._evcv.notify_all()
        self._thread.join(timeout or 10.0)
        with self._lock:
            leftovers = ([f for f in self._inflight.values()] +
                         [f for f in self._parked])
        for freq in leftovers:
            self._fail(freq, 'cancelled',
                       EngineClosedError('fleet router closed'))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
