"""GenerationEngine: continuous (iteration-level) batching for LLM decode.

``InferenceEngine`` batches whole requests; autoregressive generation
can't wait for a batch — requests arrive ragged, produce different
numbers of tokens, and a fixed-batch ``generate()`` call head-of-line
blocks every sequence on the longest one. This engine schedules at the
*iteration* level (the Orca discipline, PAPERS.md arxiv 2309.06180 /
2604.15464): a fixed number of decode **slots** runs ONE compiled decode
step per iteration, and the host scheduler admits new sequences into free
slots and retires finished ones *between* steps. Two executables serve
the whole workload:

 - ``prefill``: batch-1, prompts padded to a fixed ``prefill_width`` —
   one program for every prompt length (pad rows are routed to the paged
   pool's trash page and the last REAL row's logits sample token 0);
 - ``step``: all ``num_slots`` rows advance one token — inactive slots
   decode garbage into the trash page and their sample is discarded.

KV state lives in a paged pool (``ops/paged_kv.py``): fixed-size pages
in one shared buffer, a per-slot page table, and a host-side free-list
allocator, so slot occupancy — not worst-case sequence length — bounds
HBM. Pages are allocated lazily at each page boundary; on exhaustion the
most-recently-admitted active slot — possibly the requester itself — is
evicted (pages freed, request requeued at the queue FRONT), so the oldest
sequence always advances and no pair of growing sequences can livelock
each other. Sampling keys are derived per slot as
``fold_in(PRNGKey(seed), position)``, so a restarted sequence
regenerates byte-identical tokens and its future never re-emits ones
already streamed.

Robustness / telemetry reuse the serving stack: bounded admission queue
(``QueueFullError``), per-request deadlines (``DeadlineExceededError``),
a ``fault.CircuitBreaker`` + ``gen.step`` chaos point around device
calls, ``gen.*`` metrics in the observability registry, and warmup
manifest capture (``gen_prefill`` / ``gen_decode`` entries) so a new
process prebuilds both executables before traffic.

With ``prefix_cache=True`` the engine indexes finished sequences' pages
in a :class:`~.prefix_cache.PrefixCache` (tenant-namespaced trie over
page-aligned chunks): a later request with a cached prefix is admitted
with those pages pre-mapped and prefills only the uncached tail through
the SAME prefill executable (the tail start position is a traced
argument — zero new traces, provable via ``_trace_count``); an exact
``(prompt, seed)`` repeat skips the prefill device call entirely and
replays the recorded first token (near-zero TTFT). Shared pages are
refcounted by the allocator; mid-page divergence copies the page
(copy-on-write) before any write, and cache residency is released LRU
before any live slot is ever evicted for pages.

Env knobs: ``PADDLE_TPU_GEN_SLOTS`` (default 8),
``PADDLE_TPU_GEN_PAGE_SIZE`` (default 128, clamped to max_seq_len),
``PADDLE_TPU_GEN_PREFIX`` (=1 enables the prefix cache by default).
"""
import functools as _functools
import itertools
import os
import sys
import threading
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from .. import fault
from .. import observability as _obs
from ..models import gpt as _gpt
from ..ops import paged_kv as _pkv
from .errors import DeadlineExceededError, EngineClosedError, QueueFullError
from .prefix_cache import PrefixCache

ENV_SLOTS = 'PADDLE_TPU_GEN_SLOTS'
ENV_PAGE_SIZE = 'PADDLE_TPU_GEN_PAGE_SIZE'
ENV_PREFIX = 'PADDLE_TPU_GEN_PREFIX'

_HIST_WINDOW = 4096

# sentinel distinguishing "deadline not supplied" from "no deadline": the
# fleet router must be able to resubmit a deadline-free request as such
_UNSET = object()


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class GenerationFuture:
    """Handle for one submitted sequence. ``result()`` blocks for the full
    token list; ``stream()`` yields tokens as decode iterations emit them.
    Eviction/readmission never re-yields: regenerated tokens are only
    appended past what the future already holds."""

    def __init__(self):
        self._cv = threading.Condition()
        self._tokens = []
        self._done = False
        self._exc = None
        self._listeners = []

    # ---- engine-internal ------------------------------------------------
    def _count(self):
        with self._cv:
            return len(self._tokens)

    def _snapshot(self, n):
        """First ``n`` emitted tokens (the prefix-cache publisher's view of
        what the KV rows past the prompt hold)."""
        with self._cv:
            return [int(t) for t in self._tokens[:n]]

    def _subscribe(self, fn):
        """Register ``fn(kind, *args)`` invoked OUTSIDE the future's lock:
        ``('token', idx, tok)`` per emission and ``('finish', exc)`` once.
        Tokens already emitted are replayed so a late subscriber (a fleet
        router attaching to a resubmitted request) misses nothing. Callers
        must tolerate out-of-order delivery across the replay/live seam —
        the index identifies each token's position."""
        with self._cv:
            self._listeners.append(fn)
            replay = list(enumerate(self._tokens))
            done, exc = self._done, self._exc
        for i, t in replay:
            fn('token', i, t)
        if done:
            fn('finish', exc)

    def _append(self, tok):
        with self._cv:
            if self._done:
                return
            self._tokens.append(int(tok))
            idx = len(self._tokens) - 1
            listeners = list(self._listeners)
            self._cv.notify_all()
        # listeners run outside the lock: they may touch other futures /
        # router queues whose locks must never nest inside this one
        for fn in listeners:
            fn('token', idx, int(tok))

    def _finish(self, exc=None):
        with self._cv:
            if self._done:
                return False
            self._done = True
            self._exc = exc
            listeners = list(self._listeners)
            self._cv.notify_all()
        for fn in listeners:
            fn('finish', exc)
        return True

    # ---- caller API -----------------------------------------------------
    def done(self):
        with self._cv:
            return self._done

    def exception(self, timeout=None):
        with self._cv:
            if not self._cv.wait_for(lambda: self._done, timeout):
                raise TimeoutError('generation still running')
            return self._exc

    def result(self, timeout=None):
        exc = self.exception(timeout)
        if exc is not None:
            raise exc
        with self._cv:
            return list(self._tokens)

    def stream(self, timeout=None):
        """Generator of tokens in emission order; returns at EOS/limit,
        raises the failure exception if the sequence failed."""
        i = 0
        while True:
            with self._cv:
                if not self._cv.wait_for(
                        lambda: self._done or i < len(self._tokens), timeout):
                    raise TimeoutError('generation stalled')
                if i < len(self._tokens):
                    tok = self._tokens[i]
                    i += 1
                elif self._exc is not None:
                    raise self._exc
                else:
                    return
            yield tok


class _Request:
    __slots__ = ('prompt', 'eff_max_new', 'seed', 'future', 'enqueue_t',
                 'deadline_t', 'evictions', 'ttft_noted', 'rec', 'tenant')

    def __init__(self, prompt, eff_max_new, seed, future, enqueue_t,
                 deadline_t, rec=None, tenant='default'):
        self.prompt = prompt
        self.eff_max_new = eff_max_new
        self.seed = seed
        self.tenant = tenant
        self.future = future
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        self.evictions = 0
        self.ttft_noted = False
        # request-scoped trace record (observability.reqtrace); the shared
        # no-op singleton when the layer is disabled
        self.rec = rec if rec is not None else _obs.NULL_RECORD


class _Slot:
    __slots__ = ('req', 'pos', 'last_tok', 'produced', 'table', 'admit_seq',
                 'start', 'cow', 'first_tok')

    def __init__(self, req, table, admit_seq, start=0, cow=None,
                 first_tok=None):
        self.req = req
        self.pos = len(req.prompt)      # next KV write position
        self.last_tok = 0
        self.produced = 0
        self.table = table              # np [p_max] i32, 0 = unallocated
        self.admit_seq = admit_seq
        self.start = start              # first prompt row prefill computes
                                        # (cached rows < start are mapped)
        self.cow = cow                  # pending (src, dst) page copy
        self.first_tok = first_tok      # full prefix hit: replay this token
                                        # instead of running prefill


def _resolve_generation_model(net, config, forward_fn):
    """Accept a GPTForCausalLM-style Layer (has .config + _params) or a
    (params, config) functional pair; infer the forward fn from the config
    family when not given."""
    if config is None:
        cfg = getattr(net, 'config', None)
        if cfg is None:
            raise TypeError(
                'GenerationEngine needs a model with a .config or an '
                'explicit (params, config) pair')
        if hasattr(net, '_decode_params'):
            params = net._decode_params()
        else:
            params = net._params()
    else:
        params, cfg = net, config
    if forward_fn is None:
        if 'moe' in type(cfg).__name__.lower():
            from ..models import moe_gpt
            forward_fn = moe_gpt.forward_with_cache
        else:
            forward_fn = _gpt.forward_with_cache
    params = jax.tree_util.tree_map(jnp.asarray, params)
    return params, cfg, forward_fn


class GenerationEngine:
    """Continuous-batching generation over one causal-LM model.

    ``submit(prompt)`` returns a ``GenerationFuture`` immediately; the
    scheduler thread prefills it into a free slot and advances it one
    token per decode iteration alongside every other active sequence.
    Sampling knobs (temperature/top_k/top_p, greedy by default) are
    engine-wide — one executable — while the RNG seed is per-request.
    """

    _seq = itertools.count()

    def __init__(self, net, config=None, *, num_slots=None, page_size=None,
                 num_pages=None, prefill_width=None, temperature=0.0,
                 top_k=None, top_p=None, eos_id=None, queue_capacity=64,
                 default_deadline_ms=None, breaker=None, autostart=True,
                 forward_fn=None, clock=None, precision=None,
                 telemetry_port=None, prefix_cache=None,
                 prefix_cache_pages=None, mesh=None, mp=None):
        if os.environ.get('PADDLE_TPU_COMPILE_CACHE'):
            from .. import warmup as _warmup_mod
            _warmup_mod.ensure_persistent_cache()
        if precision not in (None, 'float32', 'int8_wo'):
            raise ValueError(
                f"GenerationEngine precision must be None/'float32'/"
                f"'int8_wo', got {precision!r}")
        params, cfg, fwd = _resolve_generation_model(net, config, forward_fn)
        if precision == 'int8_wo':
            from ..ops.weight_only import is_weight_only
            if not is_weight_only(params.get('wte')):
                # family-matched snapshot (qkv/proj/mlp/wte int8, per-output-
                # channel scales); a model already snapshot (e.g. via
                # enable_int8_decode) passes through untouched
                if 'moe' in type(cfg).__name__.lower():
                    from ..models import moe_gpt as _fam
                else:
                    _fam = _gpt
                params = _fam.quantize_decode_params(params)
        # mesh-sharded replica (mp=N): ONE SPMD program over N chips.
        # Params are placed by the logical-axis rules table, the forward
        # pins the KV pool to the kv_heads layout, and everything else —
        # scheduler, allocator, page tables, trace count — is the mp=1
        # code verbatim (parallel/mesh_engine.py).
        from ..parallel import mesh_engine as _mesh
        self._mesh_ctx = _mesh.resolve(mesh, mp=mp)
        if self._mesh_ctx is not None:
            if precision == 'int8_wo':
                raise ValueError(
                    'mesh-sharded engines do not support precision='
                    "'int8_wo' yet: the quantized bank pytree has no "
                    'logical-axis annotations to place')
            params = self._mesh_ctx.place_params(params, cfg)
            fwd = _functools.partial(
                fwd, partitioner=self._mesh_ctx.partitioner)
        self._params = params
        self.config = cfg
        self._forward_fn = fwd
        self._precision = precision or 'float32'

        s_max = int(cfg.max_seq_len)
        self.max_seq_len = s_max
        self.num_slots = int(num_slots if num_slots is not None
                             else _env_int(ENV_SLOTS, 8))
        ps = int(page_size if page_size is not None
                 else min(_env_int(ENV_PAGE_SIZE, 128), s_max))
        if ps < 1:
            raise ValueError(f'page_size must be >= 1, got {ps}')
        self.page_size = ps
        self.p_max = _pkv.pages_for(s_max, ps)
        self.prefill_width = int(prefill_width if prefill_width is not None
                                 else s_max)
        if not 1 <= self.prefill_width <= s_max:
            raise ValueError(
                f'prefill_width {self.prefill_width} outside '
                f'[1, {s_max}]')
        # +1: page 0 is the reserved trash page
        self.num_pages = int(num_pages if num_pages is not None
                             else self.num_slots * self.p_max + 1)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self.queue_capacity = int(queue_capacity)
        self.default_deadline_ms = default_deadline_ms
        self._breaker = breaker if breaker is not None else \
            fault.CircuitBreaker(failure_threshold=5, recovery_timeout=5.0)
        self._clock = clock or time.monotonic
        self._autostart = autostart

        self._pool = self._init_pool()
        self._alloc = _pkv.PageAllocator(self.num_pages)
        # prefix cache: opt-in (constructor flag, giving it a residency
        # bound, or the env knob) — page accounting changes when finished
        # sequences stay resident, so it is never silently enabled
        if prefix_cache is None:
            prefix_cache = (prefix_cache_pages is not None
                            or _env_int(ENV_PREFIX, 0) > 0)
        self._prefix = (PrefixCache(self._alloc, ps, prefix_cache_pages)
                        if prefix_cache else None)
        self._slots = [None] * self.num_slots
        self._queue = deque()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._thread = None
        self._closed = False
        self._draining = False
        self._admit_seq = 0
        self._trace_count = 0
        self._fns = None
        # kind -> AOT Compiled executable, seeded by warmup/prebuild; the
        # live path prefers these (a jit callable's first real call would
        # still pay the executable build even when the trace is cached)
        self._aot = {}
        self._start_t = self._clock()
        self._n = {k: 0 for k in ('submitted', 'completed', 'rejected',
                                  'expired', 'failed', 'evictions',
                                  'tokens', 'prefills', 'steps',
                                  'prefix_hits', 'prefix_misses',
                                  'prefix_full_hits', 'prefix_tokens_saved',
                                  'prefix_evictions')}
        self._make_metrics()
        # readiness + optional telemetry plane (same contract as
        # InferenceEngine: /readyz = warm AND breaker closed AND queue
        # below capacity; telemetry_port=0 picks a free port)
        self._warmed = False
        self._probe_name = f'serving.{self.labels["engine"]}'
        _obs.add_readiness(self._probe_name, self._readiness_probe)
        self.telemetry = (_obs.serve_telemetry(port=telemetry_port)
                          if telemetry_port is not None else _obs.NULL_SERVER)

    def _init_pool(self):
        """Fresh paged-KV pool, head-sharded over the mesh when one is
        active (the allocator and page tables stay host-side either way)."""
        pool = _gpt.init_paged_kv_cache(self.config, self.num_pages,
                                        self.page_size)
        if self._mesh_ctx is not None:
            pool = self._mesh_ctx.place_pool(pool)
        return pool

    def _readiness_probe(self):
        with self._lock:
            depth = len(self._queue)
            closed = self._closed
        warm = (self._warmed or self._fns is not None
                or ('gen_prefill' in self._aot and 'gen_decode' in self._aot))
        breaker = self._breaker.state
        ready = (warm and breaker == 'closed'
                 and depth < self.queue_capacity and not closed)
        return {'ready': ready, 'warm': warm, 'breaker': breaker,
                'queue_depth': depth, 'queue_capacity': self.queue_capacity,
                'closed': closed}

    # ---- telemetry -------------------------------------------------------
    def _make_metrics(self):
        # UNIFORMITY: the label set is identical at every mesh degree —
        # fleet/host/SLO lookups key on exactly {'engine': ...}, and the
        # registry matches label sets exactly, so adding a mesh label here
        # would silently detach every control-plane rule from an mp>1
        # replica. The mesh degree is published as its own gauge series
        # (gen.mesh_devices, labelled engine+mesh) for /metrics slicing.
        labels = self.labels = {'engine': f'g{next(GenerationEngine._seq)}'}
        if self._mesh_ctx is not None and _obs.enabled():
            _obs.registry().gauge(
                'gen.mesh_devices',
                {**self.labels, 'mesh': f'mp{self._mesh_ctx.mp}'}
            ).set(self._mesh_ctx.size)
        if _obs.enabled():
            reg = _obs.registry()
            mk_c = lambda n: reg.counter(n, labels)             # noqa: E731
            mk_h = lambda n: reg.histogram(n, labels,           # noqa: E731
                                           window=_HIST_WINDOW)
            mk_g = lambda n: reg.gauge(n, labels)               # noqa: E731
        else:
            mk_c = lambda n: _obs.Counter(n, labels)            # noqa: E731
            mk_h = lambda n: _obs.Histogram(n, labels,          # noqa: E731
                                            window=_HIST_WINDOW)
            mk_g = lambda n: _obs.Gauge(n, labels)              # noqa: E731
        self._c = {k: mk_c(f'gen.requests_{k}') for k in
                   ('submitted', 'completed', 'rejected', 'expired',
                    'failed')}
        self._c['evictions'] = mk_c('gen.evictions')
        self._c['tokens'] = mk_c('gen.tokens')
        # gen.prefix.*: the prefix-cache surface fleetobs federates
        self._c['prefix_hits'] = mk_c('gen.prefix.hits')
        self._c['prefix_misses'] = mk_c('gen.prefix.misses')
        self._c['prefix_full_hits'] = mk_c('gen.prefix.full_hits')
        self._c['prefix_tokens_saved'] = mk_c('gen.prefix.tokens_saved')
        self._c['prefix_evictions'] = mk_c('gen.prefix.evictions')
        self._h = {'prefill': mk_h('gen.prefill_ms'),
                   'step': mk_h('gen.decode_step_ms'),
                   'ttft': mk_h('gen.ttft_ms'),
                   # same series the batch engines emit, labelled gN — the
                   # fleet autoscaler's per-replica p99 rules key on it.
                   # Observed at admit from the ORIGINAL enqueue_t, which
                   # requeue-after-eviction preserves: a preempted request's
                   # wait is never under-reported.
                   'queue_wait': mk_h('serve.queue_wait_ms')}
        self._g = {'occupancy': mk_g('gen.slot_occupancy'),
                   'pages': mk_g('gen.page_utilization'),
                   'prefix_pages': mk_g('gen.prefix.cached_pages')}

    def _note(self, key, n=1):
        self._n[key] += n
        c = self._c.get(key)
        if c is not None:
            c.inc(n)

    def _update_gauges_locked(self):
        active = sum(1 for s in self._slots if s is not None)
        self._g['occupancy'].set(active / max(self.num_slots, 1))
        # page 0 (the reserved trash page) is excluded from the
        # denominator: a fully loaded pool reads 1.0
        usable = max(self.num_pages - 1, 1)
        self._g['pages'].set(self._alloc.used_pages / usable)
        if self._prefix is not None:
            self._g['prefix_pages'].set(self._prefix.cached_pages)
            ev = self._prefix.stats()['evictions']
            delta = ev - self._n['prefix_evictions']
            if delta > 0:
                self._note('prefix_evictions', delta)

    # ---- compiled fns ----------------------------------------------------
    def _build_fns(self):
        cfg, fwd = self.config, self._forward_fn
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p

        def sample_rows(lg, seeds, positions):
            if temperature == 0:
                # greedy: per-row argmax, batch-composition independent
                return jnp.argmax(lg, axis=-1).astype(jnp.int32)

            def one(row, seed, p):
                # the key depends only on (seed, input position): a
                # restarted/evicted sequence regenerates identical tokens
                key = jax.random.fold_in(jax.random.PRNGKey(seed), p)
                return _gpt._sample(row[None], temperature, top_k, top_p,
                                    key=key)[0]
            return jax.vmap(one)(lg, seeds, positions)

        def prefill(params, pool, prompt, start, valid, page_table, seed):
            self._trace_count += 1      # trace-time side effect
            # 'tail': True (a STATIC pytree key — the dict never crosses a
            # jit boundary) routes T>1 attention through the paged kernel
            # so rows past ``start`` attend prefix pages written by an
            # earlier sequence. ONE executable serves cold prefills
            # (start=0) and cached-prefix tails alike: start is traced,
            # so prefix-cache hits never trace or compile anything new.
            cache = {'k': pool['k'], 'v': pool['v'],
                     'page_table': page_table, 'valid': valid, 'tail': True}
            pos0 = start.astype(jnp.int32)
            logits, cache = fwd(params, prompt, cache, pos0, cfg,
                                last_only=True)
            # absolute position start+valid-1: the sampling key of the
            # prompt's last row must not depend on how much was cached
            tok = sample_rows(logits[:, 0], seed,
                              pos0 + valid.astype(jnp.int32) - 1)
            return tok, {'k': cache['k'], 'v': cache['v']}

        def step(params, pool, tok, pos, page_table, seeds):
            self._trace_count += 1
            cache = {'k': pool['k'], 'v': pool['v'],
                     'page_table': page_table}
            logits, cache = fwd(params, tok[:, None], cache, pos, cfg)
            nxt = sample_rows(logits[:, 0], seeds, pos)
            return nxt, {'k': cache['k'], 'v': cache['v']}

        return (jax.jit(prefill, donate_argnums=(1,)),
                jax.jit(step, donate_argnums=(1,)))

    def _fns_pair(self):
        if self._fns is None:
            self._fns = self._build_fns()
        return self._fns

    def _manifest_entries(self):
        from ..warmup.manifest import generation_entry
        geom = dict(slots=self.num_slots, page_size=self.page_size,
                    num_pages=self.num_pages,
                    prefill_width=self.prefill_width,
                    table_width=self.p_max)
        return [generation_entry('gen_prefill', **geom),
                generation_entry('gen_decode', **geom)]

    def _maybe_record(self):
        wm = sys.modules.get('paddle_tpu.warmup.manifest')
        if wm is not None and wm.capturing():
            for e in self._manifest_entries():
                wm.record(e)

    def warmup(self):
        """AOT-compile the prefill and decode executables before traffic
        (zero cold-start: a live call after this neither retraces nor
        recompiles). Returns the prebuild report dict."""
        from .. import warmup as _warmup_mod
        man = _warmup_mod.Manifest()
        for e in self._manifest_entries():
            man.add(e)
        report = _warmup_mod.prebuild(man, generation=self)
        if self._prefix is not None:
            # pre-compile the COW copy executable too — a trash-page
            # self-copy is a no-op on real data, and without it the first
            # mid-page cache hit would pay the compile in its TTFT
            with self._lock:
                self._pool = _pkv.copy_page(self._pool, 0, 0)
        self._warmed = True          # flips the /readyz warm check
        return report

    # ---- lifecycle -------------------------------------------------------
    def start(self):
        with self._lock:
            if self._closed:
                raise EngineClosedError('engine already shut down')
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._scheduler_loop,
                    name='paddle-tpu-generation-sched', daemon=True)
                self._thread.start()
        return self

    def shutdown(self, drain=True, timeout=None):
        """Stop the scheduler. ``drain=True`` finishes every admitted and
        queued sequence first; otherwise their futures fail with
        EngineClosedError."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._draining = drain
            failed = []
            if not drain:
                failed = [r for r in self._queue]
                self._queue.clear()
                for i, slot in enumerate(self._slots):
                    if slot is not None:
                        failed.append(slot.req)
                        self._free_slot_locked(i)
            inline = drain and self._thread is None
            self._cv.notify_all()
        for r in failed:
            err = EngineClosedError('engine shut down')
            r.rec.note('cancel')
            r.rec.finish('cancelled', err)
            if r.future._finish(err):
                self._note('failed')
        if inline:
            self._drain_inline()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._prefix is not None:
            with self._lock:
                self._prefix.clear()
        _obs.remove_readiness(self._probe_name)
        self.telemetry.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # ---- admission -------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, deadline_ms=None, seed=0,
               tenant='default', *, _record=None, _enqueue_t=None,
               _deadline_t=_UNSET):
        """Enqueue one sequence. ``prompt`` is a 1-D token id sequence of
        length 1..prefill_width; returns a ``GenerationFuture``. Tokens
        stop at ``eos_id`` (emitted), ``max_new_tokens``, or the context
        window (a prompt of exactly max_seq_len still yields one token).
        ``tenant`` namespaces the prefix cache: KV pages are only ever
        reused within one tenant's own traffic.

        The underscore params are the fleet router's resubmission hooks:
        a failed-over request keeps its original ``RequestRecord``,
        submit-time enqueue timestamp, and absolute deadline so queue-wait
        SLO accounting and deadline enforcement stay truthful across
        replicas (timestamps must come from this engine's clock domain —
        ``time.monotonic`` unless a test injected one)."""
        arr = np.asarray(prompt, dtype=np.int32).reshape(-1)
        t0 = int(arr.size)
        if not 1 <= t0 <= self.prefill_width:
            raise ValueError(
                f'prompt length {t0} outside [1, {self.prefill_width}] '
                f'(prefill_width)')
        if int(max_new_tokens) < 1:
            raise ValueError('max_new_tokens must be >= 1')
        # the final decode write lands at position max_seq_len-1; the +1 is
        # the token sampled from that full-window step (same rule as
        # GPTForCausalLM.generate's n_cached)
        eff = min(int(max_new_tokens), self.max_seq_len - t0 + 1)
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else self.default_deadline_ms)
        now = self._clock()
        enqueue_t = _enqueue_t if _enqueue_t is not None else now
        if _deadline_t is not _UNSET:
            deadline_t = _deadline_t
        else:
            deadline_t = (now + deadline_ms / 1e3
                          if deadline_ms is not None else None)
        fut = GenerationFuture()
        # request-scoped trace: minted here, rides the request across the
        # submit -> scheduler thread boundary (NULL_RECORD when disabled)
        if _record is not None:
            rec = _record
        else:
            rec = _obs.start_request('gen', engine=self.labels['engine'],
                                     prompt_len=t0, max_new=eff)
        fut.request_id = rec.rid
        if deadline_t is not None and now >= deadline_t:
            # already unmeetable: fail fast instead of queueing a request
            # the admitter would only expire after it reached a slot
            waited = (now - enqueue_t) * 1e3
            limit = (deadline_t - enqueue_t) * 1e3
            err = DeadlineExceededError(waited, limit)
            self._note('expired')
            rec.note('expire', waited_ms=round(waited, 3), fast_fail=True)
            rec.finish('expired', err)
            raise err
        req = _Request(arr, eff, int(seed) & 0xFFFFFFFF, fut, enqueue_t,
                       deadline_t, rec=rec, tenant=str(tenant))
        try:
            with self._cv:
                if self._closed:
                    raise EngineClosedError('engine already shut down')
                if len(self._queue) >= self.queue_capacity:
                    self._note('rejected')
                    raise QueueFullError(self.queue_capacity,
                                         len(self._queue))
                rec.note('enqueue', depth=len(self._queue))
                self._queue.append(req)
                self._note('submitted')
                self._cv.notify_all()
        except Exception as e:
            rec.finish('rejected', e)
            raise
        if self._autostart and self._thread is None:
            self.start()
        return fut

    # ---- scheduler -------------------------------------------------------
    def _scheduler_loop(self):
        while True:
            with self._cv:
                while (not self._closed and not self._queue
                       and not any(s is not None for s in self._slots)):
                    self._cv.wait(0.05)
                if self._closed:
                    if not self._draining:
                        return
                    if (not self._queue
                            and not any(s is not None for s in self._slots)):
                        return
                admitted = self._admit_locked()
            for idx in admitted:
                self._prefill_one(idx)
            if any(s is not None for s in self._slots):
                self._decode_step()

    def _drain_inline(self):
        """Finish all admitted+queued work on the caller's thread (used by
        shutdown(drain=True) when no scheduler thread ever started)."""
        while True:
            with self._cv:
                if (not self._queue
                        and not any(s is not None for s in self._slots)):
                    return
                admitted = self._admit_locked()
            for idx in admitted:
                self._prefill_one(idx)
            if any(s is not None for s in self._slots):
                self._decode_step()

    def _admit_locked(self):
        out = []
        while self._queue:
            free_idx = next((i for i, s in enumerate(self._slots)
                             if s is None), None)
            if free_idx is None:
                break
            req = self._queue[0]
            now = self._clock()
            if req.deadline_t is not None and now > req.deadline_t:
                self._queue.popleft()
                waited = (now - req.enqueue_t) * 1e3
                limit = (req.deadline_t - req.enqueue_t) * 1e3
                err = DeadlineExceededError(waited, limit)
                req.rec.note('expire', waited_ms=round(waited, 3))
                req.rec.finish('expired', err)
                if req.future._finish(err):
                    self._note('expired')
                continue
            need = _pkv.pages_for(len(req.prompt), self.page_size)
            if need > self.num_pages - 1:
                self._queue.popleft()
                err = ValueError(
                    f'prompt needs {need} pages but the pool only has '
                    f'{self.num_pages - 1} allocatable')
                req.rec.finish('error', err)
                req.future._finish(err)
                self._note('failed')
                continue
            # longest cached prefix: matched full pages arrive retained
            # (this slot's references); the COW source page stays owned by
            # the cache and is copied into a private page before any write
            hit = (self._prefix.acquire(req.tenant, req.prompt, req.seed)
                   if self._prefix is not None else None)
            shared = hit['pages'] if hit else []
            cow_src = hit['cow'] if hit else None
            # the COW destination is one of the `need` logical pages and
            # comes out of the fresh allocation (pages[0] below)
            fresh = need - len(shared)
            pages = self._alloc_with_release_locked(fresh)
            if pages is None:
                if shared:
                    self._alloc.free(shared)    # undo; re-acquired on retry
                break       # active slots will free pages; retry next round
            self._queue.popleft()
            table = np.zeros((self.p_max,), np.int32)
            n_shared = len(shared)
            table[:n_shared] = shared
            cow = None
            if cow_src is not None:
                cow = (cow_src, pages[0])
                table[n_shared] = pages[0]
                pages = pages[1:]
            if pages:
                table[need - len(pages):need] = pages
            waited_ms = max(0.0, (now - req.enqueue_t) * 1e3)
            self._h['queue_wait'].observe(waited_ms)
            req.rec.note('admit', slot=free_idx, pages=need,
                         waited_ms=round(waited_ms, 3))
            start, first_tok = 0, None
            if hit is not None:
                start = hit['match']
                first_tok = hit['next_tok']
                self._note('prefix_hits')
                self._note('prefix_tokens_saved', start)
                if first_tok is not None:
                    self._note('prefix_full_hits')
                req.rec.note('prefix_hit', tokens=start,
                             full=first_tok is not None)
            elif self._prefix is not None:
                self._note('prefix_misses')
            self._slots[free_idx] = _Slot(req, table, self._admit_seq,
                                          start=start, cow=cow,
                                          first_tok=first_tok)
            self._admit_seq += 1
            out.append(free_idx)
        if out:
            self._update_gauges_locked()
        return out

    def _prefill_one(self, idx):
        slot = self._slots[idx]
        if slot is None:
            return
        req = slot.req
        t0 = len(req.prompt)
        if slot.cow is not None:
            # copy-on-write: duplicate the shared mid-page before this
            # sequence writes into it (compiled once ever — see copy_page)
            src, dst = slot.cow
            slot.cow = None
            self._pool = _pkv.copy_page(self._pool, src, dst)
        if slot.first_tok is not None:
            # full prefix hit: every prompt row is already in mapped pages
            # and the donor recorded the first sampled token for this seed
            # — no device call at all, TTFT is pure admission latency
            tok = slot.first_tok
            req.rec.note('prefill_skip', slot=idx, prompt_len=t0)
            with self._cv:
                if self._slots[idx] is not slot:
                    return
                slot.last_tok = tok
                self._emit_locked(slot, tok)
                if self._slot_finished(slot, tok):
                    self._finish_slot_locked(idx)
                self._update_gauges_locked()
            return
        start = slot.start
        tail = t0 - start               # uncached rows to prefill
        prompt = np.zeros((1, self.prefill_width), np.int32)
        prompt[0, :tail] = req.prompt[start:]
        startv = np.asarray([start], np.int32)
        valid = np.asarray([tail], np.int32)
        table = slot.table[None].copy()
        seed = np.asarray([req.seed], np.uint32)
        self._maybe_record()
        pf = self._aot.get('gen_prefill') or self._fns_pair()[0]
        wall0 = time.perf_counter()

        def dev():
            fault.inject('gen.step')
            tok, pool = pf(self._params, self._pool, jnp.asarray(prompt),
                           jnp.asarray(startv), jnp.asarray(valid),
                           jnp.asarray(table), jnp.asarray(seed))
            return int(np.asarray(tok)[0]), pool

        req.rec.note('prefill', slot=idx, prompt_len=t0, start=start)
        try:
            with _obs.span('gen.prefill', slot=idx, prompt_len=t0,
                           req_id=req.rec.rid):
                tok, pool = self._breaker.call(dev)
        except Exception as e:
            self._handle_device_failure(e)
            return
        self._pool = pool
        self._h['prefill'].observe(1e3 * (time.perf_counter() - wall0))
        self._n['prefills'] += 1
        with self._cv:
            slot.last_tok = tok
            self._emit_locked(slot, tok)
            if self._slot_finished(slot, tok):
                self._finish_slot_locked(idx)
            self._update_gauges_locked()

    def _decode_step(self):
        s = self.num_slots
        tok = np.zeros((s,), np.int32)
        pos = np.zeros((s,), np.int32)
        table = np.zeros((s, self.p_max), np.int32)
        seeds = np.zeros((s,), np.uint32)
        rids = []
        with self._cv:
            self._ensure_pages_locked()
            active = []
            for i, slot in enumerate(self._slots):
                if slot is None:
                    continue
                tok[i] = slot.last_tok
                pos[i] = slot.pos
                table[i] = slot.table
                seeds[i] = slot.req.seed
                active.append(i)
                if slot.req.rec.rid:
                    rids.append(slot.req.rec.rid)
        if not active:
            return
        self._maybe_record()
        st = self._aot.get('gen_decode') or self._fns_pair()[1]
        wall0 = time.perf_counter()

        def dev():
            fault.inject('gen.step')
            nxt, pool = st(self._params, self._pool, jnp.asarray(tok),
                           jnp.asarray(pos), jnp.asarray(table),
                           jnp.asarray(seeds))
            # ONE host readback per iteration for every slot
            return np.asarray(nxt), pool

        try:
            with _obs.span('gen.decode_step', slots=len(active),
                           req_ids=rids):
                nxt, pool = self._breaker.call(dev)
        except Exception as e:
            self._handle_device_failure(e)
            return
        self._pool = pool
        self._h['step'].observe(1e3 * (time.perf_counter() - wall0))
        self._n['steps'] += 1
        with self._cv:
            for i in active:
                slot = self._slots[i]
                if slot is None:        # evicted between snapshot and here
                    continue
                t = int(nxt[i])
                slot.pos += 1
                slot.last_tok = t
                slot.req.rec.note_decode(slot.pos)
                self._emit_locked(slot, t)
                if self._slot_finished(slot, t):
                    self._finish_slot_locked(i)
            self._update_gauges_locked()
            self._cv.notify_all()

    # ---- slot state (all called under the lock) --------------------------
    def _emit_locked(self, slot, tok):
        req = slot.req
        idx = slot.produced
        slot.produced += 1
        self._note('tokens')
        if idx >= req.future._count():
            req.future._append(tok)
            if not req.ttft_noted:
                req.ttft_noted = True
                ttft_ms = 1e3 * (self._clock() - req.enqueue_t)
                self._h['ttft'].observe(ttft_ms)
                req.rec.note('first_emit', ttft_ms=round(ttft_ms, 3))

    def _slot_finished(self, slot, tok):
        if self.eos_id is not None and tok == self.eos_id:
            return True
        if slot.produced >= slot.req.eff_max_new:
            return True
        return slot.pos >= self.max_seq_len

    def _free_slot_locked(self, idx):
        slot = self._slots[idx]
        pages = [int(p) for p in slot.table if p != _pkv.TRASH_PAGE]
        if pages:
            self._alloc.free(pages)
        self._slots[idx] = None

    def _publish_locked(self, slot):
        """Index a retiring/evicted slot's written pages in the prefix
        cache (called BEFORE the slot's own references are freed, so every
        published page is still live when the cache retains it)."""
        if self._prefix is None:
            return
        req = slot.req
        t0 = len(req.prompt)
        # KV row p >= t0 holds the (p - t0)-th generated token; the final
        # sampled token was emitted but never written, so rows == slot.pos
        gen = req.future._snapshot(slot.pos - t0)
        tokens = [int(t) for t in req.prompt] + gen
        first = (req.future._snapshot(1) or [None])[0]
        self._prefix.publish(req.tenant, tokens, slot.table, slot.pos,
                             prompt_len=t0, seed=req.seed, first_tok=first)

    def _finish_slot_locked(self, idx):
        slot = self._slots[idx]
        self._publish_locked(slot)
        self._free_slot_locked(idx)
        slot.req.rec.note('retire', produced=slot.produced,
                          evictions=slot.req.evictions)
        slot.req.rec.finish('ok')
        if slot.req.future._finish():
            self._note('completed')
        self._cv.notify_all()

    def _ensure_pages_locked(self):
        """Allocate the next page for any slot crossing a page boundary.
        On pool exhaustion, evict the most-recently-admitted active slot —
        INCLUDING the requester itself (self-preemption). The oldest
        active sequence is therefore never a victim: it monotonically
        advances, finishes, and frees its pages, which bounds every other
        sequence's wait (the no-livelock invariant — evicting "the other
        slot" instead lets two growing sequences destroy each other's
        progress forever). An evicted request requeues at the FRONT and
        later regenerates identical tokens from its seeded keys."""
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            li = slot.pos // self.page_size
            if li >= self.p_max or slot.table[li] != _pkv.TRASH_PAGE:
                continue
            while True:
                # cold cache residency yields before any live slot does
                pg = self._alloc_with_release_locked(1)
                if pg is not None:
                    slot.table[li] = pg[0]
                    break
                victim = self._pick_victim_locked()
                only = sum(1 for s in self._slots if s is not None) == 1
                if victim == i and only:
                    # alone and exhausted: this request's total demand
                    # exceeds the whole pool — retrying cannot succeed
                    self._free_slot_locked(i)
                    if slot.req.future._finish(RuntimeError(
                            f'request needs more KV pages than the pool '
                            f'holds ({self.num_pages - 1} allocatable)')):
                        self._note('failed')
                    break
                self._evict_locked(victim)
                if victim == i:
                    break       # self-preempted; re-admitted when pages free
            # fall through to the next slot whether or not i survived

    def _alloc_with_release_locked(self, n):
        """``alloc(n)``, releasing LRU prefix-cache residency on failure
        until the allocation fits or the cache is dry. A released page
        only reaches the free list at refcount zero, so keep releasing
        while the cache still holds anything."""
        pages = self._alloc.alloc(n)
        while pages is None and self._prefix is not None:
            if not self._prefix.release_lru(n):
                break
            pages = self._alloc.alloc(n)
        return pages

    def _pick_victim_locked(self):
        best, best_seq = None, -1
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            if slot.admit_seq > best_seq:
                best, best_seq = i, slot.admit_seq
        return best

    def _evict_locked(self, idx):
        slot = self._slots[idx]
        req = slot.req
        # publish what the victim already computed: its re-admission (and
        # anyone sharing its prefix) prefills only past the cached rows
        self._publish_locked(slot)
        self._free_slot_locked(idx)
        req.evictions += 1
        req.rec.note('evict', count=req.evictions)
        self._note('evictions')
        # FRONT of the queue: an evicted sequence restarts before any new
        # arrival — bounded starvation, deterministic regeneration
        self._queue.appendleft(req)

    def _handle_device_failure(self, exc):
        """A failed device call may have consumed the donated pool: fail
        every active sequence, release their pages, rebuild the pool."""
        with self._cv:
            failed = []
            for i, slot in enumerate(self._slots):
                if slot is not None:
                    failed.append(slot.req)
                    self._free_slot_locked(i)
            if self._prefix is not None:
                # cached KV lives in the pool being rebuilt: drop it all
                self._prefix.clear()
            self._pool = self._init_pool()
            self._update_gauges_locked()
            self._cv.notify_all()
        for r in failed:
            r.rec.finish('error', exc)
            if r.future._finish(exc):
                self._note('failed')

    # ---- prefix cache knobs ----------------------------------------------
    @property
    def prefix_cache(self):
        """The engine's :class:`~.prefix_cache.PrefixCache` (None when
        disabled)."""
        return self._prefix

    def set_prefix_capacity(self, capacity_pages):
        """Bound prefix-cache residency to ``capacity_pages`` pool pages
        (None lifts the bound) — the ModelHost per-model knob. Evicts LRU
        leaves immediately when already over."""
        if self._prefix is None:
            return
        with self._lock:
            self._prefix.set_capacity(capacity_pages)
            self._update_gauges_locked()

    def clear_prefix_cache(self):
        """Release every cached page back toward the allocator (pages also
        mapped by live slots free when those slots retire). Returns the
        number of entries dropped."""
        if self._prefix is None:
            return 0
        with self._lock:
            n = self._prefix.clear()
            self._update_gauges_locked()
            return n

    # ---- observability ---------------------------------------------------
    def stats(self):
        elapsed = max(self._clock() - self._start_t, 1e-9)

        def pct(h, q):
            v = h.percentile(q)
            return round(v, 3) if v is not None else 0.0

        with self._lock:
            active = sum(1 for s in self._slots if s is not None)
            depth = len(self._queue)
            free_pages = self._alloc.free_pages
        out = dict(self._n)
        out.update({
            'active_slots': active,
            'queue_depth': depth,
            'free_pages': free_pages,
            'num_slots': self.num_slots,
            'page_size': self.page_size,
            'num_pages': self.num_pages,
            'prefill_width': self.prefill_width,
            'traces': self._trace_count,
            'tokens_per_sec': round(self._n['tokens'] / elapsed, 2),
            'prefill_ms_p50': pct(self._h['prefill'], 50),
            'prefill_ms_p99': pct(self._h['prefill'], 99),
            'decode_step_ms_p50': pct(self._h['step'], 50),
            'decode_step_ms_p99': pct(self._h['step'], 99),
            'ttft_ms_p50': pct(self._h['ttft'], 50),
            'ttft_ms_p99': pct(self._h['ttft'], 99),
            'circuit_state': self._breaker.state,
            'precision': self._precision,
            'warmed': self._warmed,
            'uptime_s': round(elapsed, 3),
        })
        out['prefix'] = (self._prefix.stats()
                         if self._prefix is not None else None)
        out['mesh'] = (self._mesh_ctx.describe()
                       if self._mesh_ctx is not None else None)
        return out
