"""Bucketed executable cache.

One compiled function per ``(bucket_size, input_signature, precision)`` —
the TPP (arxiv 2104.05755) discipline of a small set of shape-stable
compiled primitives reused across the whole request stream. The builder is
supplied by the engine; the cache only owns keying and lifetime. Since
every cached function is invoked at exactly one padded shape, ``len(cache)``
IS the executable count the serve benchmark asserts on.

Locking is per key: the global lock guards only the dict bookkeeping, and
a builder runs outside it holding a per-key event — a slow compile for one
bucket never blocks hits (or concurrent compiles) for other buckets.
Concurrent requests for the *same* missing key coalesce onto one build;
if the builder raises, waiters wake and retry the build themselves.

``put()`` seeds an externally built executable (warmup AOT prebuild):
it counts in ``prebuilt`` / ``serve.prebuilt``, not in ``misses``.
"""
import threading

from .. import observability as _obs


class BucketCompileCache:
    def __init__(self, builder):
        self._builder = builder
        self._fns = {}
        self._lock = threading.Lock()
        self._building = {}  # key -> Event set when the build finishes
        self.misses = 0
        self.prebuilt = 0

    def get(self, bucket, sig, precision):
        key = (bucket, sig, precision)
        while True:
            with self._lock:
                fn = self._fns.get(key)
                if fn is not None:
                    return fn
                event = self._building.get(key)
                if event is None:
                    event = self._building[key] = threading.Event()
                    is_builder = True
                else:
                    is_builder = False
            if not is_builder:
                event.wait()
                continue
            try:
                with _obs.span('serve.compile', bucket=bucket,
                               precision=str(precision)) as sp:
                    fn = self._builder(bucket, sig, precision)
            except BaseException:
                with self._lock:
                    self._building.pop(key, None)
                event.set()
                raise
            with self._lock:
                self._fns[key] = fn
                self.misses += 1
                self._building.pop(key, None)
            event.set()
            _obs.counter('serve.compiles', {'bucket': str(bucket)}).inc()
            _obs.histogram('serve.compile_ms').observe(1e3 * sp.duration)
            return fn

    def peek(self, bucket, sig, precision):
        """The cached executable for a key, or None — never builds."""
        with self._lock:
            return self._fns.get((bucket, sig, precision))

    def put(self, bucket, sig, precision, fn):
        """Seed a prebuilt executable; first write wins. Returns True when
        the entry was installed."""
        key = (bucket, sig, precision)
        with self._lock:
            if key in self._fns:
                return False
            self._fns[key] = fn
            self.prebuilt += 1
        _obs.counter('serve.prebuilt', {'bucket': str(bucket)}).inc()
        return True

    def __len__(self):
        with self._lock:
            return len(self._fns)

    def keys(self):
        with self._lock:
            return list(self._fns)

    def clear(self):
        with self._lock:
            self._fns.clear()
