"""Bucketed executable cache.

One compiled function per ``(bucket_size, input_signature, precision)`` —
the TPP (arxiv 2104.05755) discipline of a small set of shape-stable
compiled primitives reused across the whole request stream. The builder is
supplied by the engine; the cache only owns keying and lifetime. Since
every cached function is invoked at exactly one padded shape, ``len(cache)``
IS the executable count the serve benchmark asserts on.
"""
import threading

from .. import observability as _obs


class BucketCompileCache:
    def __init__(self, builder):
        self._builder = builder
        self._fns = {}
        self._lock = threading.RLock()
        self.misses = 0

    def get(self, bucket, sig, precision):
        key = (bucket, sig, precision)
        with self._lock:
            fn = self._fns.get(key)
            if fn is None:
                with _obs.span('serve.compile', bucket=bucket,
                               precision=str(precision)) as sp:
                    fn = self._builder(bucket, sig, precision)
                self._fns[key] = fn
                self.misses += 1
                _obs.counter('serve.compiles',
                             {'bucket': str(bucket)}).inc()
                _obs.histogram('serve.compile_ms').observe(
                    1e3 * sp.duration)
        return fn

    def __len__(self):
        with self._lock:
            return len(self._fns)

    def keys(self):
        with self._lock:
            return list(self._fns)

    def clear(self):
        with self._lock:
            self._fns.clear()
