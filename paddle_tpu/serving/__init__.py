"""paddle_tpu.serving — dynamic-batching inference for heavy traffic.

The ROADMAP's serving path: an ``InferenceEngine`` in front of a bucketed
compile cache. Requests of uneven size (``submit()`` returns a future) are
coalesced on a background dispatch thread into padded power-of-two buckets,
so the whole request stream is served by at most
``ceil(log2(max_batch)) + 1`` XLA executables per input signature — the
Ragged-Paged-Attention / TPP serving discipline.

    from paddle_tpu import serving
    engine = serving.InferenceEngine(net, max_batch_size=16, max_delay_ms=2)
    fut = engine.submit(x)            # x: [n, ...], n >= 1
    y = fut.result(timeout=1.0)
    print(engine.stats())             # p50/p99, pad waste, occupancy, ...
    engine.shutdown()

Robustness: bounded admission queue (``QueueFullError``), per-request
deadlines (``DeadlineExceededError``, a fault.RetryError), a CircuitBreaker
around the device call, and the ``serving.dispatch`` chaos point.

Fleet serving (``fleet.py``): a ``ReplicaSet`` of N engines behind a
``FleetRouter`` front door — health-gated least-loaded routing, failover
that loses no request and duplicates no stream token, load shedding with
a ``retry_after_ms`` hint, SLO-driven autoscaling from a warm template,
and graceful drain for zero-drop rolling restarts.

Multi-tenant hosting (``host.py``): a ``ModelHost`` owning N
heterogeneous engines behind one HBM watermark — admission measured via
``perf.hbm_bytes``, LRU eviction of cold models that keeps warmup
manifests (swap-in is seconds, zero retraces), interactive/batch
priority lanes with SLO-driven batch shedding, and per-tenant quotas +
``request.*`` accounting. The fleet router targets hosted models as
``submit(..., target='model@host')``.

Prefix caching (``prefix_cache.py``): the ``GenerationEngine`` can keep
finished sequences' paged-KV pages resident in a tenant-namespaced
``PrefixCache`` — a repeat prefix is admitted with its pages pre-mapped
(refcounted sharing + copy-on-write), prefilling only the uncached tail
and skipping prefill entirely on an exact ``(prompt, seed)`` repeat.

Sharded serving (``sharded.py``): one replica spanning an ``mp=N``
device mesh — ``sharded_generation_engine``/``MeshReplica`` place params
and the paged KV pool (heads axis) through the logical-axis rules table
and run the same two compiled programs as one SPMD executable. Streams
are byte-identical across mesh shapes at matched seeds, warm spawn and
host swap-in stay zero-retrace, and ``ModelHost.deploy(..., mp=N)``
admits by per-chip footprint.
"""
from .bucketing import (bucket_for, bucket_sizes, input_signature,  # noqa: F401
                        pad_rows)
from .bucket_cache import BucketCompileCache  # noqa: F401
from .errors import (DeadlineExceededError, EngineClosedError,  # noqa: F401
                     HBMAdmissionError, QueueFullError)
from .metrics import ServingStats  # noqa: F401
from .engine import InferenceEngine  # noqa: F401
from .generation import GenerationEngine, GenerationFuture  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .fleet import (Autoscaler, FleetRouter, Replica,  # noqa: F401
                    ReplicaSet)
from .host import (HostedModel, ModelHost, get_host,  # noqa: F401
                   resolve_target)
from .sharded import (MeshReplica, sharded_generation_engine,  # noqa: F401
                      sharded_inference_engine)

__all__ = [
    'InferenceEngine', 'ServingStats', 'BucketCompileCache',
    'GenerationEngine', 'GenerationFuture', 'PrefixCache',
    'ReplicaSet', 'FleetRouter', 'Autoscaler', 'Replica',
    'ModelHost', 'HostedModel', 'get_host', 'resolve_target',
    'MeshReplica', 'sharded_generation_engine', 'sharded_inference_engine',
    'bucket_for', 'bucket_sizes', 'pad_rows', 'input_signature',
    'QueueFullError', 'DeadlineExceededError', 'EngineClosedError',
    'HBMAdmissionError',
]
