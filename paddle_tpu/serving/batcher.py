"""Dynamic batcher bookkeeping: the request objects and the per-signature
pending queues the dispatch thread drains.

Pure data-structure logic — no device calls, no threads of its own — so
bucket/flush decisions are unit-testable without an engine. The engine owns
the lock; every method here must be called with it held.
"""
import threading
import numpy as np

from ..observability.reqtrace import NULL_RECORD as _NULL_REC
from .bucketing import input_signature


class Request:
    """One admitted unit of work: ``n`` rows sharing a per-example
    signature. Oversized submissions are split into several Requests whose
    futures are joined by ``SplitJoin``."""

    __slots__ = ('arrays', 'n', 'sig', 'future', 'enqueue_t', 'deadline_t',
                 'rec')

    def __init__(self, arrays, sig, future, enqueue_t, deadline_t, rec=None):
        self.arrays = arrays
        self.n = arrays[0].shape[0]
        self.sig = sig
        self.future = future
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        # request-scoped trace record (observability.reqtrace); a shared
        # no-op singleton when the layer is disabled
        self.rec = rec if rec is not None else _NULL_REC


class SplitJoin:
    """Joins the chunk results of a split request back into one future.
    Chunk outputs are concatenated along axis 0 in submission order; the
    first chunk failure fails the whole request."""

    def __init__(self, parent_future, n_parts):
        self.future = parent_future
        self._parts = [None] * n_parts
        self._remaining = n_parts
        self._lock = threading.Lock()
        self._failed = False

    def part(self, idx):
        return _PartFuture(self, idx)

    def _done(self, idx, outs):
        with self._lock:
            if self._failed:
                return
            self._parts[idx] = outs
            self._remaining -= 1
            if self._remaining:
                return
        joined = [np.concatenate([p[i] for p in self._parts], axis=0)
                  for i in range(len(self._parts[0]))]
        self.future.set_result(joined[0] if len(joined) == 1 else joined)

    def _failed_part(self, exc):
        with self._lock:
            if self._failed:
                return
            self._failed = True
        self.future.set_exception(exc)


class _PartFuture:
    """Future-shaped adapter a chunk Request completes into."""

    __slots__ = ('_join', '_idx')

    def __init__(self, join, idx):
        self._join = join
        self._idx = idx

    def set_result(self, outs):
        self._join._done(self._idx,
                         outs if isinstance(outs, list) else [outs])

    def set_exception(self, exc):
        self._join._failed_part(exc)


class PendingQueues:
    """FIFO queues of admitted Requests, one per input signature (only
    same-signature requests can share a padded bucket)."""

    def __init__(self):
        self._by_sig = {}
        self.depth = 0          # pending Requests across all signatures

    def push(self, req):
        self._by_sig.setdefault(req.sig, []).append(req)
        self.depth += 1

    def rows(self, sig):
        return sum(r.n for r in self._by_sig.get(sig, ()))

    def take_ready(self, now, max_batch, max_delay_s, force=False):
        """Pop one flushable group: a signature whose pending rows fill a
        max_batch bucket, whose oldest request aged past max_delay, or any
        group when ``force`` (drain). Takes head-of-line requests greedily
        while they fit ``max_batch`` rows; never splits here (submit-time
        splitting guarantees every Request fits a bucket). Returns
        ``(sig, [requests])`` or None."""
        for sig, q in self._by_sig.items():
            if not q:
                continue
            total = sum(r.n for r in q)
            aged = (now - q[0].enqueue_t) >= max_delay_s
            if not (force or aged or total >= max_batch):
                continue
            taken, rows = [], 0
            while q and rows + q[0].n <= max_batch:
                r = q.pop(0)
                taken.append(r)
                rows += r.n
            if not q:
                del self._by_sig[sig]
            self.depth -= len(taken)
            return sig, taken
        return None

    def time_until_ready(self, now, max_delay_s):
        """Seconds until the oldest pending request forces a flush; None
        when nothing is pending (wait indefinitely for a submit)."""
        oldest = None
        for q in self._by_sig.values():
            if q and (oldest is None or q[0].enqueue_t < oldest):
                oldest = q[0].enqueue_t
        if oldest is None:
            return None
        return max(0.0, max_delay_s - (now - oldest))

    def drain_all(self):
        """Pop every pending request (shutdown without drain=True fails
        them; drain=True executes them)."""
        out = []
        for q in self._by_sig.values():
            out.extend(q)
        self._by_sig.clear()
        self.depth = 0
        return out


def normalize_request(inputs):
    """Validate and host-stage one submission: every input must share the
    leading row count. Returns (list of np arrays, n_rows, signature)."""
    if not inputs:
        raise ValueError('submit() needs at least one input tensor')
    arrays = []
    for x in inputs:
        a = np.asarray(x)       # Tensor/jax/np all land here via __array__
        if a.ndim == 0:
            raise ValueError('serving inputs must have a leading batch '
                             'dimension (got a scalar)')
        arrays.append(a)
    n = arrays[0].shape[0]
    for a in arrays[1:]:
        if a.shape[0] != n:
            raise ValueError(
                f'all inputs of one request must share the batch dimension '
                f'(got {n} vs {a.shape[0]})')
    if n < 1:
        raise ValueError('empty request (0 rows)')
    return arrays, n, input_signature(arrays)
