"""Mesh-sharded serving replicas: one engine spanning N chips.

``MeshReplica`` composes the pieces PRs 7–15 left on the table into the
subsystem ROADMAP item 1 asks for — serving a model bigger than one
chip's HBM:

 - the engine's two compiled programs (padded batch-1 prefill +
   fixed-slot decode step; bucket executables for ``InferenceEngine``)
   run as SPMD programs over an mp=N device mesh,
 - params are placed by the logical-axis Partitioner rules table
   (Megatron column/row layout from 'heads'/'mlp'/'vocab'),
 - the paged KV pool is sharded along its **heads** axis
   (``kv_heads -> mp``) while page tables and the host-side refcounted
   allocator stay mesh-agnostic: one logical page = N physical
   head-shards, so admission, eviction, COW and the prefix cache are the
   mp=1 code paths verbatim.

The decisive property is *uniformity* — an mp=4 replica is
indistinguishable from an mp=1 replica to every control plane:

 - trace count stays exactly 2 for the generation engine (the SPMD
   partitioning happens inside the same two traced callables),
 - warmup manifests and AOT prebuild produce executables whose input
   shardings match the live placements (``warmup.prebuild`` lowers
   through sharding-preserving structs), so warm spawn/swap-in still
   clones ``_aot`` with zero retraces,
 - FleetRouter failover and the seeded-regeneration dedup mirror work
   across mixed mp degrees because sampling keys depend only on
   (seed, position) — an mp=4 replica regenerates the byte-identical
   stream an mp=1 replica started,
 - ModelHost admission divides the measured executable footprint by the
   mesh size (per-chip HBM against a per-chip watermark),
 - every metric series carries a ``mesh="mpN"`` label.

Usage::

    from paddle_tpu.serving import MeshReplica
    rep = MeshReplica(model, mp=4, num_slots=8, page_size=64)
    rep.warmup()
    fut = rep.submit(prompt, max_new_tokens=64, seed=7)

or, for fleet/host factories that want a plain engine::

    engine = sharded_generation_engine(model, mp=4, num_slots=8)
"""
from ..parallel import mesh_engine as _mesh
from .engine import InferenceEngine
from .generation import GenerationEngine

__all__ = ['MeshReplica', 'sharded_generation_engine',
           'sharded_inference_engine']


def sharded_generation_engine(net, config=None, *, mp, devices=None,
                              rules=None, **kwargs):
    """A GenerationEngine whose prefill/step executables span an mp-way
    mesh (mp=1 returns a plain single-chip engine — same API)."""
    ctx = _context(mp, devices, rules)
    return GenerationEngine(net, config, mesh=ctx, **kwargs)


def sharded_inference_engine(net, *, mp, devices=None, rules=None,
                             **kwargs):
    """An InferenceEngine whose bucket executables span an mp-way mesh."""
    ctx = _context(mp, devices, rules)
    return InferenceEngine(net, mesh=ctx, **kwargs)


def _context(mp, devices, rules):
    mp = int(mp)
    if mp <= 1:
        return None
    return _mesh.MeshContext.build(mp, devices=devices, rules=rules)


class MeshReplica:
    """One serving replica spanning ``mp`` chips, quacking exactly like
    the engine it wraps (attribute access delegates), plus the mesh
    surface: ``.mesh_ctx``, ``.mp``, and per-chip figures in ``stats()``.

    ``kind='generation'`` (default) wraps a continuous-batching
    GenerationEngine; ``kind='inference'`` a dynamic-batching
    InferenceEngine. Remaining kwargs pass through to the engine.
    """

    def __init__(self, net, config=None, *, mp=1, kind='generation',
                 devices=None, rules=None, **kwargs):
        if kind not in ('generation', 'inference'):
            raise ValueError(
                f"MeshReplica kind must be 'generation' or 'inference', "
                f"got {kind!r}")
        self.kind = kind
        if kind == 'generation':
            self.engine = sharded_generation_engine(
                net, config, mp=mp, devices=devices, rules=rules, **kwargs)
        else:
            if config is not None:
                raise TypeError(
                    'inference MeshReplica takes a Layer/Model/Predictor, '
                    'not a (params, config) pair')
            self.engine = sharded_inference_engine(
                net, mp=mp, devices=devices, rules=rules, **kwargs)

    # ---- mesh surface ----------------------------------------------------
    @property
    def mesh_ctx(self):
        return _mesh.mesh_of(self.engine)

    @property
    def mp(self):
        ctx = self.mesh_ctx
        return ctx.mp if ctx is not None else 1

    def stats(self):
        """Engine stats plus per-chip normalization: ``tokens_per_sec`` is
        mesh-global (one SPMD program yields one token stream), so
        ``per_chip_tokens_per_sec`` is the fair cross-shape comparison
        the fleet dashboards plot."""
        out = self.engine.stats()
        n = max(1, _mesh.mesh_size(self.engine))
        tps = out.get('tokens_per_sec')
        if tps is not None:
            out['per_chip_tokens_per_sec'] = round(tps / n, 2)
        return out

    # ---- engine delegation ----------------------------------------------
    def __getattr__(self, name):
        return getattr(self.engine, name)

    def __enter__(self):
        self.engine.start()
        return self

    def __exit__(self, *exc):
        self.engine.shutdown()
        return False
