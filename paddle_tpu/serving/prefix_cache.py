"""Prefix cache: KV reuse over the paged pool (ROADMAP item 3).

Production traffic is massively redundant — shared system prompts,
few-shot templates, and multi-turn chats re-send the same prefix tokens
on every request. The paged KV pool (ops/paged_kv) is the natural unit
of reuse: this module indexes **page-aligned prefix chunks** of finished
(or evicted) sequences so a later request with the same prefix is
admitted with those pages already mapped and prefill runs only on the
uncached tail. A full hit skips the prefill device call entirely — the
donor's recorded first token is replayed and TTFT collapses to the
admission latency (prefill compute becomes a page-table update).

Structure
---------
A trie of :class:`_Entry` nodes, one per cached chunk. A node's key is
``(parent_key, chunk_tokens)`` — exact token tuples, so there are no
hash collisions by construction — and the root key is the namespace
``(tenant,)``: cross-tenant reuse is structurally impossible because a
lookup only walks chains rooted at its own tenant (the engine itself is
the model axis — each GenerationEngine owns one cache). Interior nodes
are FULL ``page_size`` chunks; *partial* nodes cover a chunk that ends
mid-page (a prompt boundary or the last written rows of a donor).
Several entries may reference the same physical page (the donor's
prompt-end chunk and its longer written-end chunk share a page); the
refcounting :class:`~..ops.paged_kv.PageAllocator` makes that safe.

Sharing rules (decided here, enforced by the engine):

 - **Full-page chunks** are mapped read-only into the consumer's page
   table with one fresh allocator reference each. The consumer never
   writes them: its first write lands strictly past the matched prefix.
 - Any page the consumer WILL write mid-page (a partial match, or an
   exact match whose last page is not full) is returned as ``cow`` —
   the engine copies it into a private page (``ops/paged_kv.copy_page``)
   before any device call: copy-on-write on mid-page divergence.
 - A **full hit** (whole prompt covered AND the donor recorded the first
   generated token for this seed) returns ``next_tok`` so the engine
   skips prefill outright.

Residency: every page an entry maps holds one allocator reference.
``release_lru(n)`` frees cold LEAF entries (children-first, so an
interior node can never strand a reachable subtree) until ``n``
references drop; the engine calls it whenever a live allocation would
otherwise fail — live slots always win over cache residency — and
:meth:`set_capacity` bounds total residency (the ModelHost per-model
knob under its HBM watermark). The cache has its own lock for stats
readers, but mutating calls arrive under the engine lock; the
allocator's lock is a leaf below both (engine -> cache -> allocator).
"""
import threading

TRASH_PAGE = 0


class _Entry:
    __slots__ = ('key', 'parent', 'chunk', 'page', 'partial', 'next_tok',
                 'last_used')

    def __init__(self, key, parent, chunk, page, partial):
        self.key = key
        self.parent = parent        # parent _Entry or None (root chunk)
        self.chunk = chunk          # tuple of token ids this node covers
        self.page = int(page)       # physical page id (one allocator ref)
        self.partial = bool(partial)
        self.next_tok = {}          # seed -> first token generated after
                                    # the EXACT prompt ending at this node
        self.last_used = 0


class PrefixCache:
    """Trie/hash index of cached prefix pages over one engine's pool."""

    def __init__(self, allocator, page_size, capacity_pages=None):
        self._alloc = allocator
        self.page_size = int(page_size)
        self._capacity = (int(capacity_pages) if capacity_pages is not None
                          else None)
        self._entries = {}          # key -> _Entry
        self._children = {}         # parent key (incl. (tenant,)) -> {keys}
        self._pages_held = 0        # allocator references this cache holds
        self._tick = 0
        self._lock = threading.RLock()
        self._n = {'insertions': 0, 'evictions': 0, 'hits': 0, 'misses': 0,
                   'full_hits': 0}

    # ---- introspection ---------------------------------------------------
    @property
    def cached_pages(self):
        """Allocator references held (two entries on one physical page
        count twice — this is the residency the allocator sees)."""
        with self._lock:
            return self._pages_held

    @property
    def capacity_pages(self):
        return self._capacity

    def stats(self):
        with self._lock:
            out = dict(self._n)
            out['entries'] = len(self._entries)
            out['cached_pages'] = self._pages_held
            out['capacity_pages'] = self._capacity
            return out

    def debug_pages(self, tenant=None):
        """{tenant: sorted physical page ids} (one tenant when given) —
        the cross-tenant isolation gate asserts these sets are disjoint."""
        with self._lock:
            out = {}
            for e in self._entries.values():
                ns = self._root_tenant(e)
                if tenant is not None and ns != tenant:
                    continue
                out.setdefault(ns, set()).add(e.page)
            return {ns: sorted(pages) for ns, pages in out.items()}

    @staticmethod
    def _root_tenant(e):
        while e.parent is not None:
            e = e.parent
        return e.key[0][0]          # a root entry's parent key is (tenant,)

    # ---- capacity --------------------------------------------------------
    def set_capacity(self, capacity_pages):
        """Bound total residency; evicts LRU leaves immediately when over
        (the ModelHost per-model knob)."""
        with self._lock:
            self._capacity = (int(capacity_pages)
                              if capacity_pages is not None else None)
            if self._capacity is not None:
                over = self._pages_held - self._capacity
                if over > 0:
                    self._evict_leaves_locked(over)

    # ---- lookup / acquire ------------------------------------------------
    def acquire(self, tenant, prompt, seed):
        """Longest cached prefix of ``prompt`` under ``tenant``.

        Returns ``None`` on a miss, else a dict:
          ``pages``    — page ids to map read-only, in logical order; each
                         already carries a fresh allocator reference owned
                         by the caller (freed via normal slot teardown)
          ``match``    — prompt tokens covered by ``pages`` plus the COW
                         page: the engine's prefill start position
          ``cow``      — physical page to copy-on-write into the logical
                         slot after ``pages`` (it contains the matched
                         rows past the full pages and WILL be written by
                         the consumer), or None. NOT retained — the cache
                         keeps holding it; the caller copies, not shares.
          ``next_tok`` — the donor's first generated token when the WHOLE
                         prompt is covered and was recorded for ``seed``
                         (the skip-prefill full-hit path), else None.

        When the whole prompt is covered but no ``next_tok`` is known for
        this seed, the match is trimmed to ``len(prompt) - 1`` so at least
        one token re-prefills (the engine needs the last row's logits) —
        the final page becomes the COW source since the re-prefilled row
        lands mid-page."""
        prompt = [int(t) for t in prompt]
        t0 = len(prompt)
        ps = self.page_size
        skey = int(seed) & 0xFFFFFFFF
        with self._lock:
            self._tick += 1
            chain = []
            parent_key = (tenant,)
            for i in range(t0 // ps):
                chunk = tuple(prompt[i * ps:(i + 1) * ps])
                e = self._entries.get((parent_key, chunk))
                if e is None:
                    break
                chain.append(e)
                parent_key = e.key
            match = len(chain) * ps
            rest = tuple(prompt[match:])
            next_tok = None
            cow_entry = None
            if rest:
                cow_entry, next_tok = self._best_partial_locked(
                    parent_key, rest, skey)
            elif chain:
                # page-aligned prompt fully covered by full chunks
                tok = chain[-1].next_tok.get(skey)
                if tok is not None:
                    next_tok = int(tok)
                else:
                    # unknown first token: re-prefill the last prompt token;
                    # its KV write lands in the final page -> COW it
                    cow_entry = chain.pop()
                    match -= ps
            if not chain and cow_entry is None:
                self._n['misses'] += 1
                return None
            for e in chain:
                e.last_used = self._tick
            if cow_entry is not None:
                cow_entry.last_used = self._tick
                covered = match + len(cow_entry.chunk)
                # leave >= 1 token to prefill unless next_tok skips prefill
                match = covered if next_tok is not None \
                    else min(covered, t0 - 1)
            pages = [e.page for e in chain]
            if pages:
                self._alloc.retain(pages)
            self._n['hits'] += 1
            if next_tok is not None:
                self._n['full_hits'] += 1
            return {'pages': pages, 'match': match,
                    'cow': cow_entry.page if cow_entry is not None else None,
                    'next_tok': next_tok}

    def _best_partial_locked(self, parent_key, rest, skey):
        """Longest partial child of ``parent_key`` whose chunk is a prefix
        of ``rest`` (-> COW source), plus the recorded first token when the
        chunk covers ``rest`` exactly."""
        best, best_tok = None, None
        for key in self._children.get(parent_key, ()):
            e = self._entries[key]
            if not e.partial:
                continue
            n = len(e.chunk)
            if n > len(rest) or tuple(rest[:n]) != e.chunk:
                continue
            if best is None or n > len(best.chunk):
                best = e
                best_tok = (int(e.next_tok[skey])
                            if n == len(rest) and skey in e.next_tok
                            else None)
        return best, best_tok

    # ---- publish ---------------------------------------------------------
    def publish(self, tenant, tokens, table, written, *, prompt_len=None,
                seed=None, first_tok=None):
        """Index a retiring/evicted slot's pages.

        ``tokens``: the KV-row token sequence (prompt followed by the
        generated tokens actually written); ``table``: the slot's page
        table; ``written``: rows ``0..written-1`` hold valid KV. Full
        pages become interior chunks and the final partial page (if any)
        a terminal partial chunk. When ``prompt_len``/``seed``/
        ``first_tok`` are given, the boundary at exactly ``prompt_len``
        tokens also gets an entry (a partial chunk when mid-page, sharing
        the physical page with the longer chunk) recording the donor's
        first generated token — the skip-prefill full-hit path for an
        identical ``(prompt, seed)`` resubmission.

        Each newly indexed page is retained (+1 ref); re-publishing a
        chunk already indexed is a no-op refresh of its LRU stamp, so a
        consumer retiring through the same pages it borrowed never
        double-indexes them. Never blocks on pool pressure — capacity is
        enforced by evicting LRU leaves after insertion."""
        ps = self.page_size
        tokens = [int(t) for t in tokens[:written]]
        skey = (int(seed) & 0xFFFFFFFF) if seed is not None else None
        with self._lock:
            self._tick += 1
            chain = []              # successfully indexed full-chunk entries
            parent_key, parent = (tenant,), None
            for i in range(len(tokens) // ps):
                page = int(table[i])
                if page == TRASH_PAGE:
                    break           # table hole: stop the chain here
                chunk = tuple(tokens[i * ps:(i + 1) * ps])
                parent = self._insert_locked(parent_key, parent, chunk,
                                             page, partial=False)
                chain.append(parent)
                parent_key = parent.key
            n_ok = len(chain)
            rest = tuple(tokens[n_ok * ps:])
            if rest and n_ok == len(tokens) // ps and n_ok < len(table):
                page = int(table[n_ok])
                if page != TRASH_PAGE:
                    self._insert_locked(parent_key, parent, rest, page,
                                        partial=True)
            # prompt-boundary entry for the full-hit fast path
            if (prompt_len is not None and first_tok is not None
                    and prompt_len <= len(tokens)):
                k = prompt_len // ps
                if prompt_len % ps == 0 and 0 < k <= n_ok:
                    chain[k - 1].next_tok[skey] = int(first_tok)
                elif prompt_len % ps and k <= n_ok and k < len(table):
                    page = int(table[k])
                    if page != TRASH_PAGE:
                        pkey = chain[k - 1].key if k else (tenant,)
                        pent = chain[k - 1] if k else None
                        head = tuple(tokens[k * ps:prompt_len])
                        e = self._insert_locked(pkey, pent, head, page,
                                                partial=True)
                        e.next_tok[skey] = int(first_tok)
            if self._capacity is not None:
                over = self._pages_held - self._capacity
                if over > 0:
                    self._evict_leaves_locked(over)

    def _insert_locked(self, parent_key, parent, chunk, page, partial):
        key = (parent_key, chunk)
        e = self._entries.get(key)
        if e is None:
            # retain BEFORE indexing: retaining a freed page raises, so a
            # buggy caller (publishing after release) fails loudly instead
            # of the cache aliasing whoever allocates that page next
            self._alloc.retain([page])
            e = _Entry(key, parent, chunk, page, partial)
            self._entries[key] = e
            self._children.setdefault(parent_key, set()).add(key)
            self._pages_held += 1
            self._n['insertions'] += 1
        e.last_used = self._tick
        return e

    # ---- eviction --------------------------------------------------------
    def release_lru(self, n_pages):
        """Drop cache references for up to ``n_pages`` pages, LRU leaves
        first (live allocations outrank cache residency). Returns how many
        references were dropped — a dropped page only reaches the free
        list once every live slot sharing it retires, so callers re-try
        their allocation and keep releasing while still short."""
        with self._lock:
            return self._evict_leaves_locked(n_pages)

    def _evict_leaves_locked(self, n_pages):
        dropped = 0
        while dropped < n_pages and self._entries:
            victim = None
            for e in self._entries.values():
                if self._children.get(e.key):
                    continue        # interior: evicting would strand kids
                if victim is None or e.last_used < victim.last_used:
                    victim = e
            if victim is None:      # unreachable (a trie always has leaves)
                break
            self._remove_locked(victim)
            dropped += 1
        return dropped

    def _remove_locked(self, e):
        del self._entries[e.key]
        self._children.pop(e.key, None)
        sibs = self._children.get(e.key[0])
        if sibs is not None:
            sibs.discard(e.key)
            if not sibs:
                del self._children[e.key[0]]
        self._pages_held -= 1
        self._n['evictions'] += 1
        self._alloc.free([e.page])

    def clear(self):
        """Release everything (device-failure recovery, shutdown, and the
        leak gate's drain + clear check). Returns entries released."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
            self._children.clear()
            for e in entries:
                self._alloc.free([e.page])
            self._pages_held = 0
            return len(entries)
