"""Serving telemetry as a view over the observability registry.

Extends the PR-2 profiler instrumentation (StepTimer's phase breakdown for
training) to the serving side: queue wait, execution time, end-to-end
latency, batch occupancy / pad waste, and admission-control outcomes.
Since the observability PR every series lives in the process-wide metrics
registry under ``serve.*{engine=eN}`` — ``snapshot()`` keeps the exact
``engine.stats()`` schema the README documents, but the same numbers are
now visible in ``observability.snapshot()`` / Prometheus export.
Percentiles come from the one canonical nearest-rank implementation, and
histogram windows stay bounded — a long-lived engine never grows its
telemetry without bound. When observability is disabled the stats keep
full product behavior on private, unregistered metric objects.
"""
import itertools
import time

from .. import observability as _obs

WINDOW = 4096

# latency-ish histograms store MILLISECONDS (registry-wide convention)
_HISTOGRAMS = {
    'queue_wait': 'serve.queue_wait_ms',
    'latency': 'serve.latency_ms',
    'exec': 'serve.exec_ms',
    'batch_size': 'serve.batch_size',
}
_COUNTERS = {
    'submitted': 'serve.requests_submitted',
    'completed': 'serve.requests_completed',
    'rejected': 'serve.requests_rejected',
    'expired': 'serve.requests_expired',
    'failed': 'serve.requests_failed',
    'split': 'serve.requests_split',
    'batches': 'serve.batches',
    'rows': 'serve.rows',
    'bucket_rows': 'serve.padded_rows',
}


class ServingStats:
    """Thread-safe accumulator; ``snapshot()`` is the ``engine.stats()``
    payload (schema documented in the README Serving section). Each child
    metric carries its own lock, so hot-path notes never serialize
    against unrelated series."""

    _seq = itertools.count()

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self.labels = {'engine': f'e{next(ServingStats._seq)}'}
        self._c = {}
        self._h = {}
        self.reset()

    def _make_children(self):
        if _obs.enabled():
            reg = _obs.registry()
            self._c = {k: reg.counter(name, self.labels)
                       for k, name in _COUNTERS.items()}
            self._h = {k: reg.histogram(name, self.labels, window=WINDOW)
                       for k, name in _HISTOGRAMS.items()}
        else:
            self._c = {k: _obs.Counter(name, self.labels)
                       for k, name in _COUNTERS.items()}
            self._h = {k: _obs.Histogram(name, self.labels, window=WINDOW)
                       for k, name in _HISTOGRAMS.items()}

    def reset(self):
        self._start_t = self._clock()
        self._make_children()
        for m in self._c.values():
            m.reset()
        for m in self._h.values():
            m.reset()

    # ---- recording (engine-internal) ------------------------------------
    def note_submitted(self, n=1):
        self._c['submitted'].inc(n)

    def note_split(self):
        self._c['split'].inc()

    def note_rejected(self):
        self._c['rejected'].inc()

    def note_expired(self):
        self._c['expired'].inc()

    def note_queue_wait(self, seconds):
        self._h['queue_wait'].observe(1e3 * seconds)

    def note_completed(self, latency_s):
        self._c['completed'].inc()
        self._h['latency'].observe(1e3 * latency_s)

    def note_failed(self, n=1):
        self._c['failed'].inc(n)

    def note_batch(self, rows, bucket, exec_s):
        self._c['batches'].inc()
        self._c['rows'].inc(rows)
        self._c['bucket_rows'].inc(bucket)
        self._h['exec'].observe(1e3 * exec_s)
        self._h['batch_size'].observe(rows)

    # ---- reading ---------------------------------------------------------
    def _pct_ms(self, key, q):
        v = self._h[key].percentile(q)
        return round(v, 3) if v is not None else 0.0

    def snapshot(self):
        elapsed = max(self._clock() - self._start_t, 1e-9)
        rows = self._c['rows'].value
        bucket_rows = self._c['bucket_rows'].value
        completed = self._c['completed'].value
        occ = rows / bucket_rows if bucket_rows else 0.0
        bs = self._h['batch_size']
        return {
            'submitted': self._c['submitted'].value,
            'completed': completed,
            'rejected': self._c['rejected'].value,
            'expired': self._c['expired'].value,
            'failed': self._c['failed'].value,
            'split_requests': self._c['split'].value,
            'batches': self._c['batches'].value,
            'rows': rows,
            'padded_rows': bucket_rows,
            'batch_occupancy': round(occ, 4),
            'pad_waste_pct': round(100.0 * (1.0 - occ), 2)
            if bucket_rows else 0.0,
            'avg_batch_size': round(bs.mean, 2) if bs.count else 0.0,
            'queue_wait_ms_p50': self._pct_ms('queue_wait', 50),
            'queue_wait_ms_p99': self._pct_ms('queue_wait', 99),
            'latency_ms_p50': self._pct_ms('latency', 50),
            'latency_ms_p99': self._pct_ms('latency', 99),
            'exec_ms_p50': self._pct_ms('exec', 50),
            'exec_ms_p99': self._pct_ms('exec', 99),
            'requests_per_sec': round(completed / elapsed, 2),
            'uptime_s': round(elapsed, 3),
        }
