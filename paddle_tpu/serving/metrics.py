"""Serving telemetry: per-request and per-batch counters behind one lock.

Extends the PR-2 profiler instrumentation (StepTimer's phase breakdown for
training) to the serving side: queue wait, execution time, end-to-end
latency, batch occupancy / pad waste, and admission-control outcomes.
Percentiles come from ``profiler.percentile`` so training and serving
report latency identically. Sample windows are bounded deques — a
long-lived engine never grows its telemetry without bound.
"""
import collections
import threading
import time

from ..profiler import percentile

WINDOW = 4096


class ServingStats:
    """Thread-safe accumulator; ``snapshot()`` is the ``engine.stats()``
    payload (schema documented in the README Serving section)."""

    def __init__(self, clock=None):
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self._start_t = self._clock()
            self._submitted = 0
            self._completed = 0
            self._rejected = 0
            self._expired = 0
            self._failed = 0
            self._split = 0
            self._batches = 0
            self._rows = 0
            self._bucket_rows = 0
            self._queue_wait_s = collections.deque(maxlen=WINDOW)
            self._latency_s = collections.deque(maxlen=WINDOW)
            self._exec_s = collections.deque(maxlen=WINDOW)
            self._batch_sizes = collections.deque(maxlen=WINDOW)

    # ---- recording (engine-internal) ------------------------------------
    def note_submitted(self, n=1):
        with self._lock:
            self._submitted += n

    def note_split(self):
        with self._lock:
            self._split += 1

    def note_rejected(self):
        with self._lock:
            self._rejected += 1

    def note_expired(self):
        with self._lock:
            self._expired += 1

    def note_queue_wait(self, seconds):
        with self._lock:
            self._queue_wait_s.append(seconds)

    def note_completed(self, latency_s):
        with self._lock:
            self._completed += 1
            self._latency_s.append(latency_s)

    def note_failed(self, n=1):
        with self._lock:
            self._failed += n

    def note_batch(self, rows, bucket, exec_s):
        with self._lock:
            self._batches += 1
            self._rows += rows
            self._bucket_rows += bucket
            self._exec_s.append(exec_s)
            self._batch_sizes.append(rows)

    # ---- reading ---------------------------------------------------------
    def snapshot(self):
        with self._lock:
            elapsed = max(self._clock() - self._start_t, 1e-9)
            occ = (self._rows / self._bucket_rows
                   if self._bucket_rows else 0.0)
            return {
                'submitted': self._submitted,
                'completed': self._completed,
                'rejected': self._rejected,
                'expired': self._expired,
                'failed': self._failed,
                'split_requests': self._split,
                'batches': self._batches,
                'rows': self._rows,
                'padded_rows': self._bucket_rows,
                'batch_occupancy': round(occ, 4),
                'pad_waste_pct': round(100.0 * (1.0 - occ), 2)
                if self._bucket_rows else 0.0,
                'avg_batch_size': round(
                    sum(self._batch_sizes) / len(self._batch_sizes), 2)
                if self._batch_sizes else 0.0,
                'queue_wait_ms_p50': round(
                    1e3 * percentile(self._queue_wait_s, 50), 3),
                'queue_wait_ms_p99': round(
                    1e3 * percentile(self._queue_wait_s, 99), 3),
                'latency_ms_p50': round(
                    1e3 * percentile(self._latency_s, 50), 3),
                'latency_ms_p99': round(
                    1e3 * percentile(self._latency_s, 99), 3),
                'exec_ms_p50': round(1e3 * percentile(self._exec_s, 50), 3),
                'exec_ms_p99': round(1e3 * percentile(self._exec_s, 99), 3),
                'requests_per_sec': round(self._completed / elapsed, 2),
                'uptime_s': round(elapsed, 3),
            }
