"""Bucket policy: pad ragged request batches into a small fixed set of
compiled shapes.

Ragged Paged Attention (arxiv 2604.15464) and TPP (arxiv 2104.05755) both
land on the same serving design: XLA executables are shape-specialized, so
uneven traffic must be quantized onto a ladder of power-of-two batch sizes
— ``ceil(log2(max_batch)) + 1`` executables cover every request size, and
steady-state traffic never retraces.
"""
import numpy as np


def bucket_sizes(max_batch):
    """The bucket ladder: powers of two up to ``max_batch`` (which is
    appended as the terminal bucket when it is not itself a power of two)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError(f'max_batch must be >= 1, got {max_batch}')
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return tuple(sizes)


def bucket_for(n, max_batch=None):
    """Smallest bucket holding ``n`` rows. With ``max_batch=None`` the
    ladder is unbounded (pure next power of two) — the inference.Predictor
    dynamic-batch path uses this; the engine always passes its max."""
    n = int(n)
    if n < 1:
        raise ValueError(f'need at least one row, got {n}')
    if max_batch is not None:
        if n > max_batch:
            raise ValueError(f'{n} rows exceed max_batch={max_batch}; '
                             f'split the request first')
        for b in bucket_sizes(max_batch):
            if b >= n:
                return b
    b = 1
    while b < n:
        b *= 2
    return b


def pad_rows(arr, bucket):
    """Pad ``arr`` along axis 0 up to ``bucket`` rows by repeating the last
    real row (edge padding keeps the filler in-distribution — an all-zeros
    row can push normalization layers into degenerate branches). The real
    rows are bit-identical to the input; callers slice ``out[:n]``."""
    arr = np.asarray(arr)
    n = arr.shape[0]
    if n == bucket:
        return arr
    if n > bucket:
        raise ValueError(f'{n} rows do not fit bucket {bucket}')
    pad = [(0, bucket - n)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad, mode='edge')


def input_signature(arrays):
    """Per-example signature of a request: (shape-without-batch-dim, dtype)
    per input tensor. Requests with equal signatures are batchable."""
    sig = []
    for a in arrays:
        shape = tuple(a.shape[1:])
        sig.append((shape, str(a.dtype)))
    return tuple(sig)
