"""Typed serving errors.

``DeadlineExceededError`` subclasses ``fault.RetryError`` so callers that
already classify RetryError-family timeouts (the PR-1 fault-tolerance
contract) handle an expired serving request with the same code path.
"""
from ..fault.errors import RetryError


class QueueFullError(RuntimeError):
    """Admission control rejected a request: the engine's bounded queue is
    at capacity. Explicit backpressure — the caller decides whether to shed,
    retry with backoff, or block; the engine never buffers unboundedly.

    ``retry_after_ms`` (optional) is the shedder's estimate of when
    capacity will exist again — the fleet router populates it from the
    observed queue-wait distribution when *every* replica is saturated, so
    clients can back off for a useful interval instead of guessing."""

    def __init__(self, capacity, depth, retry_after_ms=None):
        msg = (f'serving queue full ({depth}/{capacity} pending); '
               f'request rejected by admission control')
        if retry_after_ms is not None:
            msg += f'; retry after ~{retry_after_ms:.0f}ms'
        super().__init__(msg)
        self.capacity = capacity
        self.depth = depth
        self.retry_after_ms = retry_after_ms


class DeadlineExceededError(RetryError):
    """A request's deadline expired while it waited in the batching queue;
    it was dropped without touching the device."""

    def __init__(self, waited_ms, deadline_ms):
        RuntimeError.__init__(
            self, f'request deadline {deadline_ms:.1f}ms exceeded after '
            f'{waited_ms:.1f}ms in queue')
        self.attempts = 0
        self.waited_ms = waited_ms
        self.deadline_ms = deadline_ms


class EngineClosedError(RuntimeError):
    """submit() after shutdown(): the dispatch thread is gone."""


class HBMAdmissionError(RuntimeError):
    """A ModelHost refused to admit a model: its HBM footprint plus live
    usage does not fit under the host watermark, and no cold model was
    left to evict. Typed so deployment tooling can distinguish "host is
    genuinely full" from transient serve-path failures."""

    def __init__(self, model, needed_bytes, free_bytes, watermark_bytes):
        super().__init__(
            f'model {model!r} needs {needed_bytes} HBM bytes but only '
            f'{free_bytes} fit under the {watermark_bytes}-byte watermark '
            f'(no evictable cold models remain)')
        self.model = model
        self.needed_bytes = int(needed_bytes)
        self.free_bytes = int(free_bytes)
        self.watermark_bytes = int(watermark_bytes)
