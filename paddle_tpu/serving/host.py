"""ModelHost: multi-tenant, multi-model serving on one accelerator host.

The fleet layer (``fleet.py``) scales ONE model across replicas; this
module is the orthogonal axis — N heterogeneous models (batch
``InferenceEngine`` and continuous-batching ``GenerationEngine`` mixes)
sharing one host's HBM and one front door, surviving overload from
tenants that do not coordinate with each other:

- **HBM-aware admission.** A model is admitted only if its measured
  footprint (``perf.hbm_bytes`` from the engine's compiled executables,
  falling back to parameter + KV-pool bytes) plus live usage fits under
  a configurable watermark. When it does not, the host **LRU-evicts cold
  models** — drain the engine, drop weights and the engine object, keep
  the warmup manifest AND an in-process warmth snapshot (the compiled
  executables; params are traced *arguments*, so executables outlive the
  weights) — or refuses with a typed :class:`HBMAdmissionError`. Swap-in
  rebuilds from the factory and restores the warmth snapshot: seconds,
  zero retraces, provable via the new engine's trace counter.
- **Priority lanes.** Every request is ``interactive`` or ``batch``.
  Batch may occupy at most ``batch_share`` of an engine's queue, and an
  SLO rule per hosted model on interactive ``serve.queue_wait_ms`` p99
  (the same series the fleet autoscaler keys on) flips the model into
  batch-shed mode while firing: new batch work is refused with a
  ``QueueFullError`` carrying ``retry_after_ms`` from the observed
  queue-wait distribution, so interactive latency degrades last.
- **Per-tenant accounting.** ``set_quota(tenant, n)`` caps a tenant's
  concurrent in-flight requests; every request's tenant/lane ride its
  ``RequestRecord`` attrs into the flight recorder
  (``/debug/requests?tenant=``) and the ``request.*`` / ``host.*``
  counters in ``/metrics``.

Each hosted model also gets its own :class:`~..fault.CircuitBreaker`
(device failures on one model must not take the host's other models
with it) and the host exposes two chaos points: ``host.admit`` (an
armed fault aborts admission before any side effect) and ``host.evict``
(an armed fault aborts an eviction, leaving the victim live).

The fleet router targets hosted models as ``model@host``
(``FleetRouter.submit(..., target='chat@host0')``) through the
process-local registry (``get_host`` / ``resolve_target``).

``tools/tenant_drill.py`` is the acceptance gate: a 3-model host under
2x mixed-lane overload must keep interactive p99 within budget while
batch sheds, never exceed the watermark, and evict/swap-in a cold model
mid-traffic with zero lost interactive requests and zero new compiles.
"""
import itertools
import threading
import time

from .. import fault
from .. import observability as _obs
from ..fault.errors import CircuitOpenError, InjectedFault
from ..observability import slo as _slo
from .errors import (DeadlineExceededError, EngineClosedError,
                     HBMAdmissionError, QueueFullError)
from .generation import GenerationEngine

LANES = ('interactive', 'batch')

_LIVE = 'live'
_EVICTED = 'evicted'
_ADMITTING = 'admitting'
_EVICTING = 'evicting'

# hbm kinds summed into a footprint: weights+inputs (argument), workspace
# (temp), results (output), program (code)
_FOOTPRINT_KINDS = ('argument', 'temp', 'output', 'code')

_hosts_lock = threading.Lock()
_HOSTS = {}              # host name -> ModelHost


def get_host(name):
    """Look up a live :class:`ModelHost` by name (None when unknown)."""
    with _hosts_lock:
        return _HOSTS.get(name)


def resolve_target(target):
    """Parse a ``model@host`` target into ``(host, model_name)``.

    The fleet router's cross-host addressing: raises ``ValueError`` on a
    malformed target and ``KeyError`` when the host is not registered in
    this process."""
    if not isinstance(target, str) or target.count('@') != 1:
        raise ValueError(f"target must look like 'model@host', got "
                         f'{target!r}')
    model, host_name = target.split('@')
    if not model or not host_name:
        raise ValueError(f"target must look like 'model@host', got "
                         f'{target!r}')
    host = get_host(host_name)
    if host is None:
        raise KeyError(f'no ModelHost named {host_name!r} in this process')
    return host, model


def _tree_nbytes(tree):
    """Total array bytes in a pytree (0 for non-array leaves)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += int(getattr(leaf, 'nbytes', 0) or 0)
    return total


def _snapshot_warmth(engine):
    """Capture an engine's compiled executables before it is torn down.

    Both engine families pass params/buffers as traced ARGUMENTS, never
    closed-over constants, so the executables hold no weight storage and
    outlive the engine: generation AOT prefill/decode executables and
    batch-engine bucket-cache entries are both portable to a fresh engine
    built by the same factory (same geometry => same traced signatures)."""
    snap = {}
    aot = getattr(engine, '_aot', None)
    if aot:
        snap['aot'] = dict(aot)
    cache = getattr(engine, '_cache', None)
    if cache is not None:
        with cache._lock:
            if cache._fns:
                snap['buckets'] = dict(cache._fns)
    return snap


def _restore_warmth(snap, engine):
    """Seed a fresh engine with a warmth snapshot: its first request runs
    with zero retraces and zero new executables (same mechanism as the
    fleet's warm spawn)."""
    aot = snap.get('aot')
    if aot and hasattr(engine, '_aot'):
        engine._aot.update(aot)
    buckets = snap.get('buckets')
    cache = getattr(engine, '_cache', None)
    if buckets and cache is not None:
        with cache._lock:
            for key, fn in buckets.items():
                cache._fns.setdefault(key, fn)
            cache.prebuilt += len(buckets)
    engine._warmed = True


class HostedModel:
    """One model's host-side record: lifecycle state, HBM accounting,
    lane/inflight counters, per-model breaker, retained warm-up
    artifacts (manifest + warmth snapshot) across evictions."""

    __slots__ = ('name', 'factory', 'kind', 'engine', 'manifest', 'warmth',
                 'footprint_bytes', 'reserved_bytes', 'last_used', 'state',
                 'pinned', 'breaker', 'inflight', 'batch_inflight',
                 'shed_batch', 'rule_name', 'swap_ins', 'evictions',
                 'input_spec', 'prefix_cache_pages')

    def __init__(self, name, factory, *, pinned=False, input_spec=None,
                 footprint_bytes=0, breaker=None, prefix_cache_pages=None):
        self.name = name
        self.factory = factory
        self.kind = None             # 'infer' | 'gen', set at materialize
        self.engine = None
        self.manifest = None         # warmup.Manifest retained across evicts
        self.warmth = None           # in-process executable snapshot
        self.footprint_bytes = int(footprint_bytes)
        self.reserved_bytes = 0      # bytes currently counted against host
        self.last_used = None
        self.state = _ADMITTING
        self.pinned = bool(pinned)
        self.breaker = breaker if breaker is not None else \
            fault.CircuitBreaker(failure_threshold=5, recovery_timeout=5.0)
        self.inflight = 0
        self.batch_inflight = 0
        self.shed_batch = False
        self.rule_name = None
        self.swap_ins = 0
        self.evictions = 0
        self.input_spec = input_spec
        # residency bound for a generation engine's prefix cache, re-applied
        # on every swap-in (the host's lever to keep cached KV pages from
        # crowding the HBM watermark)
        self.prefix_cache_pages = prefix_cache_pages

    @property
    def engine_label(self):
        eng = self.engine
        if eng is None:
            return ''
        if self.kind == 'gen':
            return eng.labels['engine']
        return eng._stats.labels['engine']

    def describe(self):
        pc = (getattr(self.engine, 'prefix_cache', None)
              if self.engine is not None else None)
        return {'state': self.state, 'kind': self.kind,
                'footprint_bytes': self.footprint_bytes,
                'prefix_cache_pages': self.prefix_cache_pages,
                'prefix_cached_pages': (pc.cached_pages
                                        if pc is not None else 0),
                'inflight': self.inflight,
                'batch_inflight': self.batch_inflight,
                'shed_batch': self.shed_batch,
                'pinned': self.pinned,
                'breaker': self.breaker.state,
                'engine': self.engine_label,
                'swap_ins': self.swap_ins,
                'evictions': self.evictions,
                'has_manifest': self.manifest is not None,
                'has_warmth': bool(self.warmth)}


class ModelHost:
    """N engines, one HBM budget, two priority lanes, per-tenant quotas.

    ``deploy(name, factory)`` admits a model (the factory builds its
    engine — called again on swap-in after an eviction);
    ``submit(model, *args, tenant=, lane=)`` routes one request. An
    evicted model is swapped back in transparently on its next submit.
    """

    _seq = itertools.count()

    def __init__(self, hbm_watermark_bytes, *, name=None,
                 interactive_p99_ms=100.0, slo_interval=0.25,
                 slo_debounce=2, batch_share=0.5, clock=None):
        wm = int(hbm_watermark_bytes)
        if wm <= 0:
            raise ValueError('hbm_watermark_bytes must be > 0')
        self.name = name or f'host{next(ModelHost._seq)}'
        self.watermark_bytes = wm
        self.interactive_p99_ms = float(interactive_p99_ms)
        self.slo_debounce = int(slo_debounce)
        self.batch_share = float(batch_share)
        if not 0.0 < self.batch_share <= 1.0:
            raise ValueError('batch_share must be in (0, 1]')
        self._clock = clock or time.monotonic
        self._labels = {'host': self.name}
        self._lock = threading.Lock()
        self._models = {}            # name -> HostedModel (insertion order)
        self._used_bytes = 0
        self._quotas = {}            # tenant -> max concurrent in-flight
        self._tenant_inflight = {}   # tenant -> current in-flight
        self._closed = False
        self._n = {k: 0 for k in ('admitted', 'rejected', 'evictions',
                                  'swap_ins', 'shed')}
        # the host owns its SLO watcher: one interactive queue-wait p99
        # rule per hosted model drives batch-lane shedding
        self._watcher = _slo.Watcher(interval=slo_interval)
        self._watcher.start()
        self._probe_name = f'host.{self.name}'
        _obs.add_readiness(self._probe_name, self._readiness_probe)
        _obs.gauge('host.hbm_watermark_bytes', self._labels).set(wm)
        with _hosts_lock:
            _HOSTS[self.name] = self

    # ---- HBM accounting --------------------------------------------------
    def _publish_hbm_locked(self):
        _obs.gauge('host.hbm_used_bytes', self._labels).set(self._used_bytes)
        _obs.gauge('host.models_live', self._labels).set(
            sum(1 for m in self._models.values() if m.state == _LIVE))

    def _lru_cold_locked(self, exclude):
        """Least-recently-used live model with nothing in flight (the only
        safe eviction victims); None when every live model is hot/pinned."""
        cold = [m for m in self._models.values()
                if (m.state == _LIVE and not m.pinned and m.inflight == 0
                    and m.name != exclude)]
        if not cold:
            return None
        return min(cold, key=lambda m: m.last_used or 0.0)

    def _reserve(self, m, need):
        """Account ``need`` more bytes to ``m``, LRU-evicting cold models
        until it fits under the watermark; raises HBMAdmissionError when
        nothing evictable remains."""
        need = int(need)
        if need <= 0:
            return
        while True:
            with self._lock:
                free = self.watermark_bytes - self._used_bytes
                if need <= free:
                    self._used_bytes += need
                    m.reserved_bytes += need
                    self._publish_hbm_locked()
                    return
                # feasibility first: refuse before evicting anyone if the
                # request cannot fit even with every cold model gone — an
                # infeasible deploy must not strip the host bare
                evictable = sum(
                    x.reserved_bytes for x in self._models.values()
                    if (x.state == _LIVE and not x.pinned
                        and x.inflight == 0 and x.name != m.name))
                victim = (self._lru_cold_locked(exclude=m.name)
                          if need <= free + evictable else None)
                if victim is None:
                    self._n['rejected'] += 1
                    err = HBMAdmissionError(m.name, need, free,
                                            self.watermark_bytes)
                else:
                    victim.state = _EVICTING
                    err = None
            if err is not None:
                _obs.counter('host.admission_rejects', self._labels).inc()
                raise err
            self._evict_now(victim)

    def _release(self, m):
        with self._lock:
            self._used_bytes -= m.reserved_bytes
            m.reserved_bytes = 0
            self._publish_hbm_locked()

    # ---- footprint measurement -------------------------------------------
    def _measure_footprint(self, m, engine):
        """The model's PER-CHIP HBM footprint in bytes. Preference:
        measured ``perf.hbm_bytes`` from the engine's compiled executables
        (argument+temp+output+code, max over executables — weights appear
        in every executable's arguments, so max approximates residency);
        fallback: parameter/buffer/KV-pool array bytes.

        A mesh-sharded engine's cost analysis reports MESH-GLOBAL bytes
        (the SPMD program's whole-array arguments/temps/outputs), but the
        watermark is a per-chip budget: argument/temp/output divide by the
        mesh size ('code' does not — every chip holds the full program),
        so an mp=4 deploy of a 4x model does not spuriously trip
        :class:`HBMAdmissionError`. The division is the sharded-residency
        upper bound: replicated fall-through leaves make a chip hold MORE
        than total/N, which the max-over-executables argument bytes still
        dominate in practice."""
        from ..parallel.mesh_engine import mesh_size
        n_chips = max(1, mesh_size(engine))
        best = 0
        aot = getattr(engine, '_aot', None) or {}
        for kind, compiled in aot.items():
            rec = _obs.perf.analyze_compiled(
                f'host.{self.name}.{m.name}.{kind}', compiled)
            if rec:
                total = sum(
                    int(rec['hbm'].get(k, 0) or 0) // (
                        n_chips if k != 'code' else 1)
                    for k in _FOOTPRINT_KINDS)
                best = max(best, total)
        if best > 0:
            return best
        # array-bytes fallback: params/pool are the dominant terms and
        # both shard ~1/N over the mesh
        est = _tree_nbytes(getattr(engine, '_params', None))
        est += _tree_nbytes(getattr(engine, '_buffers', None))
        est += _tree_nbytes(getattr(engine, '_pool', None))
        return est // n_chips

    # ---- admission / deploy ----------------------------------------------
    def deploy(self, name, factory, *, footprint_bytes=None, input_spec=None,
               pin=False, warm=True, breaker=None, prefix_cache_pages=None,
               mp=None):
        """Admit one model onto the host.

        ``factory`` is a zero-arg callable building the model's engine —
        it is called again on swap-in after an eviction, so it must be
        repeatable. ``footprint_bytes`` pre-gates admission before the
        engine is even built (otherwise the first deploy builds, measures,
        and then enforces the watermark); ``pin=True`` exempts the model
        from LRU eviction; ``prefix_cache_pages`` caps a generation
        engine's prefix-cache residency (applied after every build, so the
        bound survives evict/swap-in cycles). Raises
        :class:`HBMAdmissionError` when the model cannot fit even after
        evicting every cold model.

        ``mp=N`` deploys a mesh-sharded replica: the factory is called as
        ``factory(mp=N)`` on every (re)build, so swap-in after an eviction
        reconstructs the same mesh shape. Admission then accounts the
        measured footprint PER CHIP against the per-chip watermark (see
        ``_measure_footprint``); warmth snapshots restore across swap-ins
        exactly like mp=1 — the executables hold no weights, only the
        placements."""
        if mp is not None:
            base_factory, mp = factory, int(mp)
            factory = lambda: base_factory(mp=mp)       # noqa: E731
        try:
            fault.inject('host.admit')
        except InjectedFault:
            _obs.counter('host.admit_faults', self._labels).inc()
            raise
        with self._lock:
            if self._closed:
                raise EngineClosedError(f'host {self.name} is closed')
            if name in self._models:
                raise ValueError(f'model {name!r} already deployed on host '
                                 f'{self.name}')
            m = HostedModel(name, factory, pinned=pin, input_spec=input_spec,
                            footprint_bytes=footprint_bytes or 0,
                            breaker=breaker,
                            prefix_cache_pages=prefix_cache_pages)
            self._models[name] = m
        try:
            if m.footprint_bytes:
                self._reserve(m, m.footprint_bytes)
            self._materialize(m, warm=warm)
        except BaseException:
            self._release(m)
            with self._lock:
                self._models.pop(name, None)
            raise
        with self._lock:
            m.state = _LIVE
            m.last_used = self._clock()
            self._n['admitted'] += 1
            self._publish_hbm_locked()
        self._register_slo(m)
        _obs.counter('host.admitted', self._labels).inc()
        _obs.record_event('host.admit', host=self.name, model=name,
                          footprint_bytes=m.footprint_bytes)
        return m

    def _materialize(self, m, warm=True):
        """Build the engine from the factory, warm it (warmth snapshot on
        swap-in, else AOT prebuild), capture the warmup manifest, and
        settle the HBM reservation against the measured footprint."""
        engine = m.factory()
        try:
            m.kind = 'gen' if isinstance(engine, GenerationEngine) \
                else 'infer'
            if m.kind == 'gen' and m.prefix_cache_pages is not None:
                engine.set_prefix_capacity(m.prefix_cache_pages)
            if m.warmth:
                # swap-in: restore the retained executables — zero
                # retraces, zero new compiles
                _restore_warmth(m.warmth, engine)
            elif warm:
                if m.kind == 'gen':
                    engine.warmup()
                else:
                    spec = m.input_spec or engine._example_spec
                    if spec is not None:
                        engine.warmup('all_buckets', input_spec=m.input_spec)
            if m.manifest is None:
                m.manifest = self._capture_manifest(m, engine)
            measured = self._measure_footprint(m, engine)
            if measured > m.footprint_bytes:
                m.footprint_bytes = measured
            extra = m.footprint_bytes - m.reserved_bytes
            if extra > 0:
                self._reserve(m, extra)
        except BaseException:
            engine.shutdown(drain=False)
            raise
        m.engine = engine

    def _capture_manifest(self, m, engine):
        """The durable cross-process swap-in artifact (the in-process
        warmth snapshot is preferred, but dies with the process)."""
        from .. import warmup as _warmup_mod
        if m.kind == 'gen':
            man = _warmup_mod.Manifest()
            for entry in engine._manifest_entries():
                man.add(entry)
            return man
        spec = m.input_spec or engine._example_spec
        if spec is None:
            return None
        return _warmup_mod.all_buckets_manifest(engine,
                                                input_spec=m.input_spec)

    # ---- eviction / swap-in ----------------------------------------------
    def evict(self, name):
        """Evict one cold model now (operator API; admission evicts LRU
        automatically). The engine drains and is dropped — weights and KV
        pool free — while the manifest and warmth snapshot are retained
        for a cheap swap-in. Refuses (RuntimeError) while requests are in
        flight."""
        with self._lock:
            m = self._models.get(name)
            if m is None:
                raise KeyError(f'unknown model {name!r} on host {self.name}')
            if m.state != _LIVE:
                return False
            if m.inflight > 0:
                raise RuntimeError(
                    f'model {name!r} has {m.inflight} requests in flight; '
                    f'only cold models can be evicted')
            m.state = _EVICTING
        self._evict_now(m)
        return True

    def _evict_now(self, m):
        """Tear down one model marked _EVICTING (never called under the
        host lock: drains the engine, which blocks)."""
        try:
            fault.inject('host.evict')
        except InjectedFault:
            _obs.counter('host.evict_faults', self._labels).inc()
            with self._lock:
                m.state = _LIVE
            raise
        t0 = time.perf_counter()
        self._remove_slo(m)
        engine = m.engine
        if engine is not None:
            snap = _snapshot_warmth(engine)
            if snap:
                m.warmth = snap
            engine.shutdown(drain=True)
        with self._lock:
            m.engine = None
            m.state = _EVICTED
            m.evictions += 1
            self._n['evictions'] += 1
            self._used_bytes -= m.reserved_bytes
            m.reserved_bytes = 0
            self._publish_hbm_locked()
        evict_ms = (time.perf_counter() - t0) * 1e3
        _obs.counter('host.evictions',
                     {**self._labels, 'model': m.name}).inc()
        _obs.histogram('host.evict_ms', self._labels).observe(evict_ms)
        _obs.record_event('host.evict', host=self.name, model=m.name,
                          evict_ms=round(evict_ms, 3))

    def admit(self, name):
        """Swap an evicted model back in (also happens transparently on
        its next ``submit``). Returns the HostedModel."""
        with self._lock:
            m = self._models.get(name)
        if m is None:
            raise KeyError(f'unknown model {name!r} on host {self.name}')
        self._swap_in(m)
        return m

    def _swap_in(self, m):
        """Re-admit an evicted model: reserve its known footprint (may LRU-
        evict others), rebuild the engine, restore warmth. Concurrent
        submitters wait on the state flag rather than a lock (no lock may
        be held across the blocking rebuild)."""
        with self._lock:
            if m.state == _LIVE:
                return
            waiter = m.state in (_ADMITTING, _EVICTING)
            if not waiter:
                m.state = _ADMITTING
        if waiter:
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                with self._lock:
                    state = m.state
                if state == _LIVE:
                    return
                if state == _EVICTED:      # the other admitter failed
                    raise EngineClosedError(
                        f'model {m.name!r} failed to swap in')
                time.sleep(0.005)
            raise TimeoutError(f'swap-in of model {m.name!r} stalled')
        try:
            fault.inject('host.admit')
        except InjectedFault:
            _obs.counter('host.admit_faults', self._labels).inc()
            with self._lock:
                m.state = _EVICTED
            raise
        t0 = time.perf_counter()
        try:
            if m.footprint_bytes:
                self._reserve(m, m.footprint_bytes)
            self._materialize(m, warm=True)
        except BaseException:
            self._release(m)
            with self._lock:
                m.state = _EVICTED
            raise
        with self._lock:
            m.state = _LIVE
            m.last_used = self._clock()
            m.swap_ins += 1
            self._n['swap_ins'] += 1
            self._publish_hbm_locked()
        self._register_slo(m)
        swap_ms = (time.perf_counter() - t0) * 1e3
        _obs.counter('host.swap_ins',
                     {**self._labels, 'model': m.name}).inc()
        _obs.histogram('host.swap_in_ms', self._labels).observe(swap_ms)
        _obs.record_event('host.swap_in', host=self.name, model=m.name,
                          swap_in_ms=round(swap_ms, 3),
                          traces=int(getattr(m.engine, '_trace_count', 0)))

    # ---- SLO lane control ------------------------------------------------
    def _register_slo(self, m):
        label = m.engine_label
        if not label:
            return
        m.rule_name = f'host.{self.name}.{m.name}.qwait'
        self._watcher.remove_rule(m.rule_name)

        def _fire(rule, value, m=m):
            with self._lock:
                m.shed_batch = True
            _obs.counter('host.slo_preempt',
                         {**self._labels, 'model': m.name}).inc()
            _obs.gauge('host.batch_shedding',
                       {**self._labels, 'model': m.name}).set(1)

        def _resolve(rule, value, m=m):
            with self._lock:
                m.shed_batch = False
            _obs.gauge('host.batch_shedding',
                       {**self._labels, 'model': m.name}).set(0)

        self._watcher.rule(m.rule_name, 'serve.queue_wait_ms',
                           self.interactive_p99_ms,
                           labels={'engine': label}, stat='p99', cmp='>',
                           debounce=self.slo_debounce,
                           on_fire=_fire, on_resolve=_resolve)

    def _remove_slo(self, m):
        if m.rule_name is not None:
            self._watcher.remove_rule(m.rule_name)
            m.rule_name = None
        if m.shed_batch:
            with self._lock:
                m.shed_batch = False
            _obs.gauge('host.batch_shedding',
                       {**self._labels, 'model': m.name}).set(0)

    def _retry_hint_ms(self, m):
        """Backoff hint from the model's observed queue-wait p99 (same
        convention as the fleet router's shed path)."""
        if _obs.enabled():
            metric = _obs.registry().find('serve.queue_wait_ms',
                                          {'engine': m.engine_label})
            if metric is not None:
                v = metric.percentile(99)
                if v:
                    return round(v, 3)
        return 50.0

    # ---- tenants ---------------------------------------------------------
    def set_quota(self, tenant, max_inflight):
        """Cap ``tenant``'s concurrent in-flight requests across every
        model on this host (None removes the cap)."""
        with self._lock:
            if max_inflight is None:
                self._quotas.pop(tenant, None)
            else:
                self._quotas[tenant] = max(0, int(max_inflight))

    def tenants(self):
        with self._lock:
            return {t: {'inflight': n, 'quota': self._quotas.get(t)}
                    for t, n in sorted(self._tenant_inflight.items())}

    # ---- front door ------------------------------------------------------
    def submit(self, model, *args, tenant='default', lane='interactive',
               deadline_ms=None, max_new_tokens=32, seed=0):
        """Route one request to a hosted model.

        ``lane='batch'`` work is capped to ``batch_share`` of the engine
        queue and shed outright (``QueueFullError.retry_after_ms``) while
        the model's interactive queue-wait SLO is firing; interactive
        work is only ever limited by the engine's own admission control
        and the tenant's quota. Submitting to an evicted model swaps it
        back in first. Generation models take ``(prompt,)`` plus
        ``max_new_tokens``/``seed``; inference models take ``*inputs``."""
        if lane not in LANES:
            raise ValueError(f'lane must be one of {LANES}, got {lane!r}')
        tenant = str(tenant)
        shed_reason = None
        with self._lock:
            if self._closed:
                raise EngineClosedError(f'host {self.name} is closed')
            m = self._models.get(model)
            if m is None:
                raise KeyError(f'unknown model {model!r} on host '
                               f'{self.name}; deployed: '
                               f'{sorted(self._models)}')
            m.last_used = self._clock()
            quota = self._quotas.get(tenant)
            cur = self._tenant_inflight.get(tenant, 0)
            cap = max(1, int(self._batch_cap(m)))
            if quota is not None and cur >= quota:
                shed_reason, shed_cap, shed_depth = 'tenant_quota', quota, cur
            elif lane == 'batch' and m.shed_batch:
                shed_reason, shed_cap, shed_depth = 'slo', cap, \
                    m.batch_inflight
            elif lane == 'batch' and m.batch_inflight >= cap:
                shed_reason, shed_cap, shed_depth = 'batch_cap', cap, \
                    m.batch_inflight
            else:
                # tentatively account the request; rolled back on any
                # submit failure below
                m.inflight += 1
                if lane == 'batch':
                    m.batch_inflight += 1
                self._tenant_inflight[tenant] = cur + 1
        if shed_reason is not None:
            self._count_shed(m, tenant, lane, shed_reason)
            raise QueueFullError(shed_cap, shed_depth,
                                 retry_after_ms=self._retry_hint_ms(m))
        try:
            if m.state != _LIVE:
                self._swap_in(m)
            if not m.breaker.allow():
                self._count_shed(m, tenant, lane, 'breaker')
                raise CircuitOpenError(m.breaker.recovery_timeout)
            engine = m.engine
            rec = _obs.start_request(
                'gen' if m.kind == 'gen' else 'serve',
                engine=m.engine_label, host=self.name, model=m.name,
                tenant=tenant, lane=lane)
            try:
                if m.kind == 'gen':
                    fut = engine.submit(args[0] if args else (),
                                        max_new_tokens=max_new_tokens,
                                        seed=seed, deadline_ms=deadline_ms,
                                        tenant=tenant, _record=rec)
                else:
                    fut = engine.submit(*args, deadline_ms=deadline_ms,
                                        _record=rec)
            except QueueFullError as e:
                # the engine finished rec ('rejected') and is alive enough
                # to apply backpressure — resolve any half-open probe as a
                # success, then re-raise with a useful backoff hint
                m.breaker.record_success()
                self._count_shed(m, tenant, lane, 'queue_full')
                if e.retry_after_ms is None:
                    raise QueueFullError(
                        e.capacity, e.depth,
                        retry_after_ms=self._retry_hint_ms(m)) from None
                raise
            except DeadlineExceededError:
                m.breaker.record_success()
                raise
            except BaseException as e:
                m.breaker.record_failure()
                rec.finish('error', e)
                raise
        except BaseException:
            self._request_done(m, tenant, lane, None, settle_breaker=False)
            raise
        self._watch_completion(m, tenant, lane, fut)
        _obs.counter('host.requests',
                     {**self._labels, 'model': m.name, 'tenant': tenant,
                      'lane': lane}).inc()
        return fut

    def _batch_cap(self, m):
        eng = m.engine
        capacity = getattr(eng, 'queue_capacity', 0) if eng is not None \
            else 16
        return capacity * self.batch_share

    def _count_shed(self, m, tenant, lane, reason):
        with self._lock:
            self._n['shed'] += 1
        _obs.counter('host.shed',
                     {**self._labels, 'model': m.name, 'tenant': tenant,
                      'lane': lane, 'reason': reason}).inc()

    def _watch_completion(self, m, tenant, lane, fut):
        """Decrement in-flight accounting and settle the model's breaker
        when the request finishes (engine threads call back here — only
        the host lock, a leaf, is taken)."""
        if m.kind == 'gen':
            def _on_event(kind, *event_args, _done=[False]):
                if kind != 'finish' or _done[0]:
                    return
                _done[0] = True
                self._request_done(m, tenant, lane,
                                   event_args[0] if event_args else None)
            fut._subscribe(_on_event)
        else:
            def _on_done(f):
                exc = None if f.cancelled() else f.exception()
                self._request_done(m, tenant, lane, exc)
            fut.add_done_callback(_on_done)

    def _request_done(self, m, tenant, lane, exc, settle_breaker=True):
        with self._lock:
            m.inflight = max(0, m.inflight - 1)
            if lane == 'batch':
                m.batch_inflight = max(0, m.batch_inflight - 1)
            cur = max(0, self._tenant_inflight.get(tenant, 1) - 1)
            if cur:
                self._tenant_inflight[tenant] = cur
            else:
                self._tenant_inflight.pop(tenant, None)
        _obs.gauge('host.tenant_inflight',
                   {**self._labels, 'tenant': tenant}).set(cur)
        if not settle_breaker:
            return
        # backpressure/deadline outcomes say nothing about model health
        if exc is None or isinstance(exc, (QueueFullError,
                                           DeadlineExceededError)):
            m.breaker.record_success()
        else:
            m.breaker.record_failure()

    # ---- introspection ---------------------------------------------------
    def _readiness_probe(self):
        with self._lock:
            live = sum(1 for m in self._models.values()
                       if m.state == _LIVE)
            closed = self._closed
            used = self._used_bytes
            states = {name: m.state for name, m in self._models.items()}
        return {'ready': live > 0 and not closed,
                'models_live': live, 'models': states,
                'hbm_used_bytes': used,
                'hbm_watermark_bytes': self.watermark_bytes,
                'closed': closed}

    def models(self):
        with self._lock:
            return {name: m.describe() for name, m in self._models.items()}

    def stats(self):
        with self._lock:
            out = dict(self._n)
            out['host'] = self.name
            out['hbm_used_bytes'] = self._used_bytes
            out['hbm_watermark_bytes'] = self.watermark_bytes
            out['models'] = {name: m.describe()
                             for name, m in self._models.items()}
            out['tenants'] = {t: {'inflight': n,
                                  'quota': self._quotas.get(t)}
                              for t, n in self._tenant_inflight.items()}
        return out

    def debug_table(self):
        """One ``/debug/fleet`` host row: HBM headroom, per-model
        residency (live/evicted, footprint, warmth retained), lane-shed
        and lifecycle counters, and per-tenant inflight vs quota — the
        operator's one-look answer to "why is this host shedding"."""
        with self._lock:
            models = {}
            for name, m in self._models.items():
                models[name] = {
                    'state': m.state, 'kind': m.kind,
                    'footprint_bytes': m.footprint_bytes,
                    'inflight': m.inflight,
                    'batch_inflight': m.batch_inflight,
                    'shed_batch': m.shed_batch,
                    'breaker': m.breaker.state,
                    'pinned': m.pinned,
                    'swap_ins': m.swap_ins,
                    'evictions': m.evictions,
                    'warm_retained': bool(m.warmth or m.manifest)}
            resident = sorted(n for n, m in self._models.items()
                              if m.state == _LIVE)
            evicted = sorted(n for n, m in self._models.items()
                             if m.state == _EVICTED)
            return {'host': self.name,
                    'hbm_watermark_bytes': self.watermark_bytes,
                    'hbm_used_bytes': self._used_bytes,
                    'hbm_free_bytes': self.watermark_bytes
                    - self._used_bytes,
                    'resident': resident, 'evicted': evicted,
                    'models': models,
                    'lane_sheds': self._n['shed'],
                    'admitted': self._n['admitted'],
                    'rejected': self._n['rejected'],
                    'evictions': self._n['evictions'],
                    'swap_ins': self._n['swap_ins'],
                    'tenants': {t: {'inflight': n,
                                    'quota': self._quotas.get(t)}
                                for t, n in
                                sorted(self._tenant_inflight.items())},
                    'closed': self._closed}

    # ---- lifecycle -------------------------------------------------------
    def undeploy(self, name, drain=True):
        """Remove a model entirely (manifest and warmth are discarded)."""
        with self._lock:
            m = self._models.pop(name, None)
        if m is None:
            return False
        self._remove_slo(m)
        engine = m.engine
        if engine is not None:
            engine.shutdown(drain=drain)
        self._release(m)
        with self._lock:
            m.engine = None
            m.state = _EVICTED
            self._publish_hbm_locked()
        return True

    def close(self, drain=True):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            models = list(self._models.values())
        for m in models:
            self._remove_slo(m)
            engine = m.engine
            if engine is not None:
                engine.shutdown(drain=drain)
            with self._lock:
                m.engine = None
        self._watcher.stop()
        _obs.remove_readiness(self._probe_name)
        with _hosts_lock:
            if _HOSTS.get(self.name) is self:
                del _HOSTS[self.name]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
