"""InferenceEngine: dynamic-batching serving front-end for a compiled model.

``submit()`` returns a future immediately; a background dispatch thread
coalesces same-signature requests into power-of-two padded buckets
(``bucketing``), executes them through a ``BucketCompileCache`` (one XLA
executable per (bucket, signature, precision) — steady-state traffic never
retraces), and slices each request's rows back out of the batched output.

Robustness is built from the PR-1 fault primitives:
 - bounded queue with explicit backpressure (``QueueFullError``),
 - per-request deadlines (``DeadlineExceededError`` — a RetryError),
 - a ``fault.CircuitBreaker`` around the device call,
 - a ``serving.dispatch`` fault-injection point for the chaos harness.

Observability: every admission/flush/latency event lands in
``ServingStats``; ``engine.stats()`` is the one-stop snapshot.

Env knobs: ``PADDLE_TPU_SERVE_MAX_BATCH`` (default 16),
``PADDLE_TPU_SERVE_MAX_DELAY_MS`` (default 2.0).
"""
import os
import sys
import threading
import time
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np

from .. import fault
from .. import observability as _obs
from .batcher import (PendingQueues, Request, SplitJoin, normalize_request)
from .bucket_cache import BucketCompileCache
from .bucketing import bucket_for, bucket_sizes, pad_rows
from .errors import DeadlineExceededError, EngineClosedError, QueueFullError
from .metrics import ServingStats

ENV_MAX_BATCH = 'PADDLE_TPU_SERVE_MAX_BATCH'
ENV_MAX_DELAY = 'PADDLE_TPU_SERVE_MAX_DELAY_MS'

_LOW_DTYPES = {'bfloat16': jnp.bfloat16, 'float16': jnp.float16}

# sentinel distinguishing "deadline not supplied" from "no deadline" on
# fleet resubmission (see submit()'s underscore params)
_UNSET = object()
# int8_wo: weights stored int8 (per-output-channel scales), dequantized
# in-trace inside each bucket's executable — activations stay full width
_PRECISIONS = ('float32', 'bfloat16', 'float16', 'int8_wo')


def _wo_param_axes(layer):
    """Dotted param name -> reduction axes for every parameter with a
    weight-only int8 layout: Linear [in, out] per-output-channel, Conv2D
    [out, in, kh, kw] per-filter, Embedding [V, H] per-row. Anything not
    listed here (biases, norms, exotic layers) stays full precision."""
    from ..nn.layer_common import Embedding, Linear
    from ..nn.layer_conv import Conv2D
    axes = {}
    for prefix, sub in layer.named_sublayers(include_self=True):
        name = f'{prefix}.weight' if prefix else 'weight'
        if isinstance(sub, Linear):
            axes[name] = (0,)
        elif isinstance(sub, Conv2D):
            axes[name] = (1, 2, 3)
        elif isinstance(sub, Embedding):
            axes[name] = (1,)
    return axes


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _resolve_backend(net, precision):
    """Accepts a Layer, a hapi Model, or an inference Predictor and returns
    (layer, params, buffers, precision, example_spec) where example_spec is
    the backend's declared input spec (hapi InputSpecs / Predictor meta) for
    ``warmup='all_buckets'``, or None when the backend declares none."""
    from ..nn.layer_base import Layer, buffer_arrays, param_arrays
    example_spec = None
    if not isinstance(net, Layer) and \
            isinstance(getattr(net, 'network', None), Layer):
        # hapi Model: flush the async executor's device-resident state back
        # into the Layer tree before we freeze a serving copy of it
        net._drain_inflight()
        net._sync_train_state()
        # flip to eval through the Model's own mode tracker: a raw
        # layer.eval() would leave _net_mode stale, making the next
        # train_batch's _enter_mode(True) a no-op (training silently
        # continuing with dropout off / BN frozen)
        net._enter_mode(False)
        example_spec = list(net._inputs) if getattr(net, '_inputs', None) \
            else None
        net = net.network
    if isinstance(net, Layer):
        return (net, param_arrays(net), buffer_arrays(net),
                precision or 'float32', example_spec)
    if hasattr(net, 'attach_layer') and hasattr(net, 'config'):
        # inference.Predictor
        pred = net
        layer = pred._layer
        if layer is None:
            raise ValueError(
                'Predictor has no attached Layer; the serving engine batches '
                'through a re-jittable forward — call attach_layer(model) '
                '(the exported .pdexec program has pinned shapes)')
        if precision is None:
            precision = pred.config._precision
            stored = pred._meta.get('precision')
            if precision == 'float32' and stored in _LOW_DTYPES:
                precision = stored   # offline-converted model: honor it
        params = {k: jnp.asarray(v) for k, v in pred._params.items()}
        buffers = {k: jnp.asarray(v) for k, v in pred._buffers.items()}
        example_spec = pred._meta.get('input_spec') or None
        return layer, params, buffers, precision or 'float32', example_spec
    raise TypeError(f'cannot serve a {type(net).__name__}; expected a '
                    f'Layer, hapi Model, or inference Predictor')


class InferenceEngine:
    """Dynamic-batching inference engine over one model.

    ``submit(*inputs)`` takes one request — every input batch-major with a
    shared leading row count (1 row is the single-query case; oversized
    requests are split across buckets transparently). Returns a
    ``concurrent.futures.Future`` resolving to the sliced outputs (a single
    array, or a list when the model has several outputs).
    """

    def __init__(self, net=None, *, max_batch_size=None, max_delay_ms=None,
                 queue_capacity=256, precision=None, default_deadline_ms=None,
                 breaker=None, autostart=True, clock=None, warmup=None,
                 input_spec=None, telemetry_port=None, mesh=None, mp=None):
        if os.environ.get('PADDLE_TPU_COMPILE_CACHE'):
            from .. import warmup as _warmup_mod
            _warmup_mod.ensure_persistent_cache()
        layer, params, buffers, precision, example_spec = \
            _resolve_backend(net, precision)
        if precision not in _PRECISIONS:
            raise ValueError(f'precision must be one of {_PRECISIONS}, '
                             f'got {precision!r}')
        layer.eval()    # serving is per-sample: BN/dropout must be frozen
        self._layer = layer
        self._precision = precision
        low = _LOW_DTYPES.get(precision)
        self._low = low
        self._wo_dtypes = {}    # quantized param name -> original dtype
        if precision == 'int8_wo':
            from ..ops.weight_only import quantize_param
            axes = _wo_param_axes(layer)
            qp = {}
            for k, v in params.items():
                if k in axes and jnp.issubdtype(v.dtype, jnp.floating):
                    qp[k] = quantize_param(v, axes[k])
                    self._wo_dtypes[k] = v.dtype
                else:
                    qp[k] = v
            params = qp

        def lower(tree):
            if low is None:
                return tree
            # buffers too: an f32 BN running stat would re-promote
            # activations back to f32 mid-network (same rule as Predictor)
            return {k: (v.astype(low)
                        if jnp.issubdtype(v.dtype, jnp.floating) else v)
                    for k, v in tree.items()}
        self._params = lower(params)
        self._buffers = lower(buffers)
        # mesh-sharded replica (mp=N): bucket executables become ONE SPMD
        # program over N chips. Params place by each Parameter's
        # ``logical_axes`` annotation through the mesh partitioner
        # (un-annotated / indivisible params replicate — memory, never
        # correctness); request arrays stay replicated host inputs.
        from ..parallel import mesh_engine as _mesh
        self._mesh_ctx = _mesh.resolve(mesh, mp=mp)
        if self._mesh_ctx is not None:
            ctx = self._mesh_ctx
            annot = {}
            for n, p in layer.named_parameters():
                la = getattr(p, 'logical_axes', None)
                if la is not None:
                    annot[n] = tuple(la)
            rep = ctx.replicated()

            def put(k, v):
                if isinstance(v, dict):
                    # int8_wo bank: quantized planes carry no logical
                    # axes — replicate (memory cost only)
                    return jax.device_put(v, rep)
                return jax.device_put(
                    v, ctx.sharding(annot.get(k),
                                    getattr(v, 'shape', None), label=k))
            self._params = {k: put(k, v) for k, v in self._params.items()}
            self._buffers = {k: jax.device_put(v, rep)
                             for k, v in self._buffers.items()}

        self.max_batch_size = int(max_batch_size if max_batch_size is not None
                                  else _env_int(ENV_MAX_BATCH, 16))
        delay_ms = (max_delay_ms if max_delay_ms is not None
                    else _env_float(ENV_MAX_DELAY, 2.0))
        self.max_delay_s = max(0.0, float(delay_ms) / 1e3)
        self.queue_capacity = int(queue_capacity)
        self.default_deadline_ms = default_deadline_ms
        self._breaker = breaker if breaker is not None else \
            fault.CircuitBreaker(failure_threshold=5, recovery_timeout=5.0)
        self._clock = clock or time.monotonic
        self._autostart = autostart

        self._cache = BucketCompileCache(self._build)
        self._trace_count = 0        # trace-time side effect: retraces show
        self._stats = ServingStats(clock=self._clock)
        if self._mesh_ctx is not None and _obs.enabled():
            # the mesh degree rides a dedicated gauge — the engine's own
            # label set stays {'engine': ...} so every fleet/host/SLO
            # exact-match lookup treats mp=N exactly like mp=1
            _obs.registry().gauge(
                'serve.mesh_devices',
                {**self._stats.labels, 'mesh': f'mp{self._mesh_ctx.mp}'}
            ).set(self._mesh_ctx.size)
        self._queues = PendingQueues()
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._thread = None
        self._closed = False
        self._draining = False
        self._example_spec = input_spec if input_spec is not None \
            else example_spec
        # readiness + optional telemetry plane: the engine advertises one
        # /readyz probe (warm AND breaker closed AND queue below capacity);
        # telemetry_port=N additionally starts the HTTP server (0 = pick a
        # free port, read it back from engine.telemetry.port)
        self._warmed = False
        self._probe_name = f'serving.{self._stats.labels["engine"]}'
        _obs.add_readiness(self._probe_name, self._readiness_probe)
        self.telemetry = (_obs.serve_telemetry(port=telemetry_port)
                          if telemetry_port is not None else _obs.NULL_SERVER)
        if warmup is not None:
            # precompile before submit() is ever accepted: the first real
            # request must find its executable already in the bucket cache
            self.warmup(warmup)

    # ---- compile path ----------------------------------------------------
    def _build(self, bucket, sig, precision):
        """One jitted forward per cache key. Params/buffers are traced
        arguments (shared device residency across every bucket), not
        closed-over constants — six buckets must not mean six HBM copies of
        the weights."""
        from ..nn.layer_base import functional_call
        layer, low = self._layer, self._low
        wo_dtypes = self._wo_dtypes

        def infer(params, buffers, *xs):
            self._trace_count += 1
            if low is not None:
                xs = [x.astype(low)
                      if jnp.issubdtype(x.dtype, jnp.floating) else x
                      for x in xs]
            if wo_dtypes:
                # int8_wo: weights live in HBM as int8; the dequant traces
                # INTO the executable so XLA fuses convert*scale into the
                # consumers' operand reads (bytes moved stay int8-sized)
                from ..ops.weight_only import dequantize_param
                params = dict(params)
                for k, dt in wo_dtypes.items():
                    params[k] = dequantize_param(params[k], dt)
            out, _ = functional_call(layer, params, buffers, *xs)
            return out
        wm = sys.modules.get('paddle_tpu.warmup.manifest')
        if wm is not None and wm.capturing():
            wm.record(wm.serving_bucket_entry(
                bucket, sig, precision, max_batch=self.max_batch_size))
        return jax.jit(infer)

    def warmup(self, manifest='all_buckets', input_spec=None):
        """AOT-precompile serving executables before traffic.

        ``manifest`` is a ``warmup.Manifest``, a path to a saved one, or
        the string ``'all_buckets'`` to synthesize the whole bucket ladder
        for one input signature (``input_spec=`` per-example
        ``(shape, dtype)`` pairs, or the spec inferred from a hapi Model /
        Predictor backend). Returns the prebuild report dict."""
        from .. import warmup as _warmup_mod
        if isinstance(manifest, str) and manifest == 'all_buckets':
            manifest = _warmup_mod.all_buckets_manifest(
                self, input_spec=input_spec)
        report = _warmup_mod.prebuild(manifest, engine=self)
        self._warmed = True          # flips the /readyz warm check
        return report

    # ---- readiness -------------------------------------------------------
    def _readiness_probe(self):
        """The engine's /readyz contribution: warm (explicit warmup ran, or
        traffic has already compiled at least one bucket) AND circuit
        breaker closed AND queue below capacity AND not shut down."""
        with self._lock:
            depth = self._queues.depth
            closed = self._closed
        warm = self._warmed or len(self._cache) > 0
        breaker = self._breaker.state
        ready = (warm and breaker == 'closed'
                 and depth < self.queue_capacity and not closed)
        return {'ready': ready, 'warm': warm, 'breaker': breaker,
                'queue_depth': depth, 'queue_capacity': self.queue_capacity,
                'closed': closed}

    # ---- lifecycle -------------------------------------------------------
    def start(self):
        with self._lock:
            if self._closed:
                raise EngineClosedError('engine already shut down')
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._dispatch_loop,
                    name='paddle-tpu-serving-dispatch', daemon=True)
                self._thread.start()
        return self

    def shutdown(self, drain=True, timeout=None):
        """Stop the dispatch thread. ``drain=True`` executes everything
        already admitted first; otherwise pending futures fail with
        EngineClosedError."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._draining = drain
            # no dispatch thread (autostart=False, never submitted-to after
            # manual start): nobody else will execute the admitted work, so
            # drain it inline here rather than leaving waiters hanging
            inline = drain and self._thread is None
            failed = [] if drain else self._queues.drain_all()
            self._cv.notify_all()
        for r in failed:
            err = EngineClosedError('engine shut down')
            r.rec.note('cancel')
            r.rec.finish('cancelled', err)
            r.future.set_exception(err)
        if inline:
            self._drain_inline()
        if self._thread is not None:
            self._thread.join(timeout)
        _obs.remove_readiness(self._probe_name)
        self.telemetry.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # ---- admission -------------------------------------------------------
    def submit(self, *inputs, deadline_ms=None,
               _record=None, _enqueue_t=None, _deadline_t=_UNSET):
        """Enqueue one request. The underscore params are the fleet
        router's resubmission hooks: a failed-over request keeps its
        original ``RequestRecord``, submit-time enqueue timestamp, and
        absolute deadline so queue-wait accounting and deadline
        enforcement stay truthful across replicas."""
        arrays, n, sig = normalize_request(inputs)
        deadline_ms = (deadline_ms if deadline_ms is not None
                       else self.default_deadline_ms)
        now = self._clock()
        enqueue_t = _enqueue_t if _enqueue_t is not None else now
        if _deadline_t is not _UNSET:
            deadline_t = _deadline_t
        else:
            deadline_t = (now + deadline_ms / 1e3
                          if deadline_ms is not None else None)
        future = Future()
        # request-scoped trace: one record per submit(), shared by every
        # chunk of a split request (NULL_RECORD when obs is disabled)
        if _record is not None:
            rec = _record
        else:
            rec = _obs.start_request(
                'serve', engine=self._stats.labels['engine'], rows=n)
        future.request_id = rec.rid
        if deadline_t is not None and now >= deadline_t:
            # already unmeetable: fail fast instead of queueing a request
            # that would only burn a dispatch slot before expiring
            waited = (now - enqueue_t) * 1e3
            limit = (deadline_t - enqueue_t) * 1e3
            err = DeadlineExceededError(waited, limit)
            self._stats.note_expired()
            rec.note('expire', waited_ms=round(waited, 3), fast_fail=True)
            rec.finish('expired', err)
            raise err
        max_b = self.max_batch_size
        if n <= max_b:
            chunks = [(arrays, future)]
        else:
            # split an oversized request into bucket-sized chunks joined
            # back into the caller's single future
            bounds = list(range(0, n, max_b)) + [n]
            join = SplitJoin(future, len(bounds) - 1)
            chunks = [([a[lo:hi] for a in arrays], join.part(i))
                      for i, (lo, hi) in enumerate(zip(bounds, bounds[1:]))]
            rec.expect_parts(len(chunks))
        try:
            with self._cv:
                if self._closed:
                    raise EngineClosedError('engine already shut down')
                depth = self._queues.depth
                if depth + len(chunks) > self.queue_capacity:
                    self._stats.note_rejected()
                    raise QueueFullError(self.queue_capacity, depth)
                rec.note('enqueue', depth=depth, chunks=len(chunks))
                for arrs, fut in chunks:
                    self._queues.push(
                        Request(arrs, sig, fut, enqueue_t, deadline_t,
                                rec=rec))
                # split requests are accounted per admitted chunk so
                # submitted/completed/occupancy all measure the same unit
                self._stats.note_submitted(len(chunks))
                if len(chunks) > 1:
                    self._stats.note_split()
                self._cv.notify_all()
        except Exception as e:
            rec.finish('rejected', e)
            raise
        if self._autostart and self._thread is None:
            self.start()
        return future

    # ---- dispatch --------------------------------------------------------
    def _dispatch_loop(self):
        while True:
            group = None
            with self._cv:
                while True:
                    now = self._clock()
                    force = self._closed
                    group = self._queues.take_ready(
                        now, self.max_batch_size, self.max_delay_s,
                        force=force)
                    if group is not None:
                        break
                    if self._closed:
                        return
                    wait = self._queues.time_until_ready(now,
                                                         self.max_delay_s)
                    # a fake test clock never advances real time: cap the
                    # sleep so aged groups are still noticed promptly
                    self._cv.wait(wait if wait is None
                                  else min(max(wait, 1e-4), 0.05))
            self._run_group(group)

    def _run_group(self, group):
        try:
            self._execute(*group)
        except BaseException as e:     # never kill the dispatch thread
            for r in group[1]:
                r.rec.finish('error', e)
                if not _future_done(r.future):
                    r.future.set_exception(e)
            self._stats.note_failed(len(group[1]))

    def _drain_inline(self):
        """Execute everything already admitted on the caller's thread (used
        by shutdown(drain=True) when no dispatch thread ever started)."""
        while True:
            with self._cv:
                group = self._queues.take_ready(
                    self._clock(), self.max_batch_size, self.max_delay_s,
                    force=True)
            if group is None:
                return
            self._run_group(group)

    def _execute(self, sig, reqs):
        now = self._clock()
        live = []
        for r in reqs:
            if r.deadline_t is not None and now > r.deadline_t:
                waited = (now - r.enqueue_t) * 1e3
                limit = (r.deadline_t - r.enqueue_t) * 1e3
                err = DeadlineExceededError(waited, limit)
                r.rec.note('expire', waited_ms=round(waited, 3))
                r.rec.finish('expired', err)
                r.future.set_exception(err)
                self._stats.note_expired()
            else:
                live.append(r)
                self._stats.note_queue_wait(now - r.enqueue_t)
        if not live:
            return
        rows = sum(r.n for r in live)
        bucket = bucket_for(rows, self.max_batch_size)
        for r in live:
            r.rec.note('admit', bucket=bucket, batch_rows=rows)
        n_in = len(live[0].arrays)
        cols = [np.concatenate([r.arrays[i] for r in live], axis=0)
                if len(live) > 1 else live[0].arrays[i]
                for i in range(n_in)]
        padded = [pad_rows(c, bucket) for c in cols]
        t0 = time.perf_counter()
        misses_before = self._cache.misses
        fn_holder = {}

        def device_call():
            fault.inject('serving.dispatch')
            fn = self._cache.get(bucket, sig, self._precision)
            fn_holder['fn'] = fn
            out = fn(self._params, self._buffers, *padded)
            outs = list(out) if isinstance(out, (list, tuple)) else [out]
            # ONE host readback for the whole batch, then host-side slicing
            return [np.asarray(o) for o in outs]

        span_kw = {'bucket': bucket, 'rows': rows, 'requests': len(live)}
        if _obs.enabled():
            # request IDs on the span: follow one request through Perfetto
            span_kw['req_ids'] = [r.rec.rid for r in live if r.rec.rid]
        try:
            with _obs.span('serve.batch', **span_kw):
                outs = self._breaker.call(device_call)
        except Exception as e:
            for r in live:
                r.rec.finish('error', e)
                r.future.set_exception(e)
            self._stats.note_failed(len(live))
            return
        exec_s = time.perf_counter() - t0
        blbl = {'bucket': str(bucket)}
        perf_label = f'serving.bucket{bucket}'
        if self._cache.misses > misses_before:
            # first execution at this bucket: includes trace+compile cost
            _obs.histogram('serve.first_exec_ms', blbl).observe(1e3 * exec_s)
        else:
            _obs.histogram('serve.bucket_exec_ms', blbl).observe(1e3 * exec_s)
            # steady-state wall time only — a compile-inclusive first exec
            # would poison the live MFU join
            _obs.perf.note_step(perf_label, exec_s,
                                precision=self._precision)
        if _obs.enabled() and _obs.perf.analyzed(perf_label) is None:
            # cache hit on the executable: publishes perf.flops{fn}/
            # perf.hbm_bytes{fn,kind}/intensity for this bucket
            _obs.perf.analyze(perf_label, fn_holder['fn'],
                              (self._params, self._buffers, *padded),
                              precision=self._precision)
        _obs.counter('serve.bucket_rows', blbl).inc(rows)
        _obs.counter('serve.bucket_padded_rows', blbl).inc(bucket)
        done_t = self._clock()
        off = 0
        for r in live:
            res = [o[off:off + r.n] if (getattr(o, 'ndim', 0) >= 1
                                        and o.shape[0] == bucket) else o
                   for o in outs]
            off += r.n
            r.future.set_result(res[0] if len(res) == 1 else res)
            r.rec.note('retire', rows=r.n, bucket=bucket)
            if r.rec.part_retired():
                r.rec.finish('ok')
            self._stats.note_completed(done_t - r.enqueue_t)
        self._stats.note_batch(rows=rows, bucket=bucket, exec_s=exec_s)

    # ---- observability ---------------------------------------------------
    def stats(self):
        out = self._stats.snapshot()
        with self._lock:
            out['queue_depth'] = self._queues.depth
        out['compiles'] = len(self._cache)
        out['cache_misses'] = self._cache.misses
        out['prebuilt'] = self._cache.prebuilt
        out['traces'] = self._trace_count
        out['buckets'] = list(bucket_sizes(self.max_batch_size))
        out['max_batch_size'] = self.max_batch_size
        out['max_delay_ms'] = self.max_delay_s * 1e3
        out['precision'] = self._precision
        out['circuit_state'] = self._breaker.state
        out['warmed'] = self._warmed
        out['mesh'] = (self._mesh_ctx.describe()
                       if self._mesh_ctx is not None else None)
        return out


def _future_done(fut):
    done = getattr(fut, 'done', None)
    return done() if callable(done) else False
